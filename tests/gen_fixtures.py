"""Deterministic test-fixture generator.

The reference ships binary fixtures in testdata/ (SURVEY.md section 4.5:
imaginary.jpg 550x740, large.jpg 1920x1080, test.png, test.webp,
smart-crop.jpg, 1024bytes). We generate equivalents procedurally so the repo
carries no opaque binaries and fixtures are reproducible: seeded gradients
plus geometric shapes, saved via PIL (the independent codec oracle — the
framework's own codec layer is never used to produce fixtures).
"""

from __future__ import annotations

import os

import numpy as np
from PIL import Image


def _base_array(w: int, h: int, seed: int) -> np.ndarray:
    """Gradient background + deterministic rectangles/disks, HWC uint8."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    r = (xx * 255.0 / max(w - 1, 1)).astype(np.uint8)
    g = (yy * 255.0 / max(h - 1, 1)).astype(np.uint8)
    b = ((xx + yy) * 255.0 / max(w + h - 2, 1)).astype(np.uint8)
    img = np.stack([r, g, b], axis=-1)
    for _ in range(6):
        x0, y0 = int(rng.integers(0, w)), int(rng.integers(0, h))
        bw, bh = int(rng.integers(w // 8, w // 3)), int(rng.integers(h // 8, h // 3))
        color = rng.integers(0, 256, size=3)
        img[y0 : min(y0 + bh, h), x0 : min(x0 + bw, w)] = color
    for _ in range(4):
        cx, cy = int(rng.integers(0, w)), int(rng.integers(0, h))
        rad = int(rng.integers(min(w, h) // 12, min(w, h) // 5))
        mask = (xx - cx) ** 2 + (yy - cy) ** 2 <= rad * rad
        img[mask] = rng.integers(0, 256, size=3)
    return img


def _smart_crop_array(w: int, h: int) -> np.ndarray:
    """Flat background with one high-contrast salient patch off-centre, so
    smartcrop tests have an unambiguous attention target."""
    img = np.full((h, w, 3), 230, dtype=np.uint8)
    cx, cy, rad = int(w * 0.75), int(h * 0.3), min(w, h) // 8
    yy, xx = np.mgrid[0:h, 0:w]
    mask = (xx - cx) ** 2 + (yy - cy) ** 2 <= rad * rad
    img[mask] = (200, 30, 30)
    ring = ((xx - cx) ** 2 + (yy - cy) ** 2 <= (rad + 6) ** 2) & ~mask
    img[ring] = (10, 10, 10)
    return img


def generate_all(out_dir: str) -> None:
    os.makedirs(out_dir, exist_ok=True)

    def save(arr: np.ndarray, name: str, **kw) -> None:
        path = os.path.join(out_dir, name)
        if not os.path.exists(path):
            Image.fromarray(arr).save(path, **kw)

    # Same dimensions as the reference fixtures (server_test.go, image_test.go).
    save(_base_array(550, 740, seed=1), "imaginary.jpg", quality=90)
    save(_base_array(1920, 1080, seed=2), "large.jpg", quality=92)
    save(_base_array(1024, 768, seed=3), "medium.jpg", quality=90)
    save(_base_array(512, 512, seed=4), "test.png")
    save(_base_array(512, 512, seed=5), "test.webp", quality=90)
    save(_base_array(320, 240, seed=6), "test.gif")
    save(_smart_crop_array(800, 600), "smart-crop.jpg", quality=92)

    # EXIF orientation-6 fixture (90 deg CW needed to display upright):
    # a 400x300 sensor image tagged orientation 6 -> upright size 300x400.
    exif_path = os.path.join(out_dir, "exif-orient-6.jpg")
    if not os.path.exists(exif_path):
        im = Image.fromarray(_base_array(400, 300, seed=7))
        exif = Image.Exif()
        exif[274] = 6  # 274 = Orientation tag
        im.save(exif_path, quality=90, exif=exif)

    # SVG fixture (the reference ships flyio-button.svg; ours is a small
    # deterministic vector with known intrinsic size + colors).
    svg_path = os.path.join(out_dir, "button.svg")
    if not os.path.exists(svg_path):
        with open(svg_path, "wb") as f:
            f.write(
                b'<svg xmlns="http://www.w3.org/2000/svg" width="240" height="160">'
                b'<rect x="0" y="0" width="240" height="160" fill="#102030"/>'
                b'<rect x="20" y="40" width="200" height="80" rx="12" fill="#e03131"/>'
                b'<circle cx="120" cy="80" r="24" fill="#2f9e44"/></svg>'
            )

    # AVIF fixture via PIL's avif plugin (skipped silently if absent).
    avif_path = os.path.join(out_dir, "test.avif")
    if not os.path.exists(avif_path):
        try:
            Image.fromarray(_base_array(320, 240, seed=8)).save(avif_path, quality=85)
        except Exception:
            pass

    # Minimal single-page PDF (240x160 pt red rectangle) written by hand —
    # enough for MediaBox probing everywhere and poppler rendering where
    # poppler-glib exists.
    pdf_path = os.path.join(out_dir, "page.pdf")
    if not os.path.exists(pdf_path):
        content = b"1 0 0 RG 0.88 0.19 0.19 rg 20 40 200 80 re f"
        objs = [
            b"<< /Type /Catalog /Pages 2 0 R >>",
            b"<< /Type /Pages /Kids [3 0 R] /Count 1 >>",
            b"<< /Type /Page /Parent 2 0 R /MediaBox [0 0 240 160] "
            b"/Contents 4 0 R >>",
            b"<< /Length " + str(len(content)).encode() + b" >>\nstream\n"
            + content + b"\nendstream",
        ]
        out = bytearray(b"%PDF-1.4\n")
        offsets = []
        for i, body in enumerate(objs, start=1):
            offsets.append(len(out))
            out += str(i).encode() + b" 0 obj\n" + body + b"\nendobj\n"
        xref_at = len(out)
        out += b"xref\n0 " + str(len(objs) + 1).encode() + b"\n"
        out += b"0000000000 65535 f \n"
        for off in offsets:
            out += ("%010d 00000 n \n" % off).encode()
        out += (
            b"trailer\n<< /Size " + str(len(objs) + 1).encode()
            + b" /Root 1 0 R >>\nstartxref\n" + str(xref_at).encode()
            + b"\n%%EOF\n"
        )
        with open(pdf_path, "wb") as f:
            f.write(bytes(out))

    # Exactly 1024 bytes of non-image data (size-limit fixture,
    # source_http_test.go:270-298).
    kb_path = os.path.join(out_dir, "1024bytes")
    if not os.path.exists(kb_path):
        with open(kb_path, "wb") as f:
            f.write(bytes(range(256)) * 4)


if __name__ == "__main__":
    generate_all(os.path.join(os.path.dirname(os.path.abspath(__file__)), "testdata"))
    print("fixtures written")
