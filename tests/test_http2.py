"""HTTP/2 serving (web/http2.py): ALPN negotiation, stream decode,
loopback bridging, and the http/1.1 fallback — graded end-to-end with
curl's OWN nghttp2-backed client as the independent protocol oracle
(the reference gets h2 from Go's net/http; server.go:114-131)."""

import os
import shutil
import socket
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.skipif(
    shutil.which("curl") is None
    or b"HTTP2" not in subprocess.run(["curl", "-V"], capture_output=True).stdout
    and b"nghttp2" not in subprocess.run(["curl", "-V"], capture_output=True).stdout,
    reason="curl with HTTP/2 support unavailable",
)


def _lib_present() -> bool:
    from imaginary_tpu.web.http2 import load_nghttp2

    return load_nghttp2() is not None


@pytest.fixture(scope="module")
def h2_server(tmp_path_factory, testdata):
    if not _lib_present():
        pytest.skip("libnghttp2 not present")
    if shutil.which("openssl") is None:
        pytest.skip("openssl unavailable for test certs")
    tmp = tmp_path_factory.mktemp("h2")
    cert, key = str(tmp / "cert.pem"), str(tmp / "key.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-keyout", key,
         "-out", cert, "-days", "2", "-nodes", "-subj", "/CN=localhost"],
        check=True, capture_output=True,
    )
    from tests.conftest import free_port
    port = free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "imaginary_tpu", "--port", str(port),
         "--certfile", cert, "--keyfile", key],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    base = f"https://127.0.0.1:{port}"
    deadline = time.time() + 90
    up = False
    while time.time() < deadline:
        r = subprocess.run(["curl", "-sk", "-o", "/dev/null", "-w", "%{http_code}",
                            base + "/health"], capture_output=True, timeout=10)
        if r.stdout == b"200":
            up = True
            break
        if proc.poll() is not None:
            break
        time.sleep(1.0)
    if not up:
        out = proc.stdout.read().decode(errors="replace") if proc.poll() is not None else ""
        proc.kill()
        pytest.fail(f"h2 test server failed to start: {out[-2000:]}")
    yield base, os.path.join(testdata, "large.jpg")
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def _curl(args, timeout=60):
    return subprocess.run(["curl", "-sk"] + args, capture_output=True, timeout=timeout)


def test_h2_negotiated_and_resize_correct(h2_server, tmp_path):
    base, img = h2_server
    out = str(tmp_path / "out.jpg")
    r = _curl(["--http2", "-o", out, "-w", "%{http_version} %{http_code} %{content_type}",
               "-F", f"file=@{img}", base + "/resize?width=300&height=200"])
    ver, code, ctype = r.stdout.decode().split()
    assert (ver, code, ctype) == ("2", "200", "image/jpeg")
    from PIL import Image

    assert Image.open(out).size == (300, 200)  # PIL is the dims oracle


def test_http11_fallback_same_port(h2_server, tmp_path):
    base, img = h2_server
    out = str(tmp_path / "out.jpg")
    r = _curl(["--http1.1", "-o", out, "-w", "%{http_version} %{http_code}",
               "-F", f"file=@{img}", base + "/resize?width=300&height=200"])
    ver, code = r.stdout.decode().split()
    assert (ver, code) == ("1.1", "200")
    from PIL import Image

    assert Image.open(out).size == (300, 200)


def test_h2_error_semantics_preserved(h2_server):
    base, img = h2_server
    # missing params -> the service's own 400, not a protocol error
    r = _curl(["--http2", "-o", "/dev/null", "-w", "%{http_version} %{http_code}",
               "-X", "POST", base + "/resize?width=100"])
    assert r.stdout.decode().split() == ["2", "400"]
    r = _curl(["--http2", "-o", "/dev/null", "-w", "%{http_version} %{http_code}",
               base + "/nonexistent"])
    assert r.stdout.decode().split() == ["2", "404"]


def test_h2_multiplexed_streams(h2_server, tmp_path):
    """curl --parallel multiplexes streams over one connection; every
    stream must come back whole. Bodies ride --data-binary, not -F:
    curl 7.88's parallel mode sends EMPTY bodies for all but one
    transfer when a form upload is repeated (reproduced over plain
    HTTP/1.1 against aiohttp alone, so it is the client, not us)."""
    base, img = h2_server
    args = ["--http2", "--parallel", "--parallel-max", "8",
            "-H", "Content-Type: image/jpeg"]
    for i in range(6):
        args += ["-o", str(tmp_path / f"p{i}.jpg"),
                 "--data-binary", f"@{img}",
                 base + f"/resize?width={100 + 10 * i}&height=80"]
    r = _curl(args, timeout=120)
    assert r.returncode == 0
    from PIL import Image

    for i in range(6):
        assert Image.open(str(tmp_path / f"p{i}.jpg")).size == (100 + 10 * i, 80)


def test_forwarded_identity_needs_hop_token(monkeypatch):
    """The access log honors X-Forwarded-* ONLY with the per-process hop
    token: a client-supplied XFF (from loopback or anywhere) must not
    forge the logged peer, while the terminator's token-bearing hop must."""
    import asyncio
    import io

    from aiohttp.test_utils import TestClient, TestServer

    from imaginary_tpu.web import accesslog
    from imaginary_tpu.web.app import create_app
    from imaginary_tpu.web.config import ServerOptions

    monkeypatch.setattr(accesslog, "_TRUSTED_HOP_TOKEN", "")

    async def scenario():
        out = io.StringIO()
        app = create_app(ServerOptions(), log_stream=out)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            # 1) spoof without any token configured: ignored
            await client.get("/health", headers={"X-Forwarded-For": "6.6.6.6"})
            # 2) token configured, client spoofs XFF but not the token: ignored
            accesslog.set_trusted_hop_token("sekrit")
            await client.get("/health", headers={"X-Forwarded-For": "6.6.6.6"})
            # 3) the real hop: token + XFF -> trusted
            await client.get("/health", headers={
                "X-Forwarded-For": "198.51.100.7",
                "X-Forwarded-HTTP-Version": "2.0",
                "X-Internal-Hop": "sekrit",
            })
        finally:
            await client.close()
        return out.getvalue().splitlines()

    lines = asyncio.run(scenario())
    assert "6.6.6.6" not in lines[0] and "6.6.6.6" not in lines[1]
    assert "198.51.100.7" in lines[2] and "HTTP/2.0" in lines[2]


def test_h2_active_respects_disable_flag():
    from imaginary_tpu.web.app import _h2_active
    from imaginary_tpu.web.config import ServerOptions

    assert _h2_active(ServerOptions(http2=False)) is False
    assert _h2_active(ServerOptions()) is _lib_present()


def test_alpn_list_tracks_h2_support(tmp_path):
    """make_ssl_context must never advertise a protocol the server cannot
    speak: h2 appears iff the terminator is active."""
    import ssl as ssl_mod

    from imaginary_tpu.web.app import make_ssl_context
    from imaginary_tpu.web.config import ServerOptions

    if shutil.which("openssl") is None:
        pytest.skip("openssl unavailable")
    cert, key = str(tmp_path / "c.pem"), str(tmp_path / "k.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-keyout", key,
         "-out", cert, "-days", "2", "-nodes", "-subj", "/CN=localhost"],
        check=True, capture_output=True,
    )
    o_on = ServerOptions(cert_file=cert, key_file=key, http2=True)
    o_off = ServerOptions(cert_file=cert, key_file=key, http2=False)
    assert isinstance(make_ssl_context(o_on), ssl_mod.SSLContext)
    assert isinstance(make_ssl_context(o_off), ssl_mod.SSLContext)
    # ALPN lists are write-only in the ssl module; negotiate against
    # ourselves to observe the difference
    for o, expect in ((o_on, "h2" if _lib_present() else "http/1.1"),
                      (o_off, "http/1.1")):
        server_ctx = make_ssl_context(o)
        client_ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_CLIENT)
        client_ctx.check_hostname = False
        client_ctx.verify_mode = ssl_mod.CERT_NONE
        client_ctx.set_alpn_protocols(["h2", "http/1.1"])
        left, right = socket.socketpair()
        try:
            import threading

            srv_result = {}

            def srv():
                try:
                    s = server_ctx.wrap_socket(left, server_side=True)
                    srv_result["alpn"] = s.selected_alpn_protocol()
                    s.close()
                except Exception as e:  # pragma: no cover
                    srv_result["err"] = e

            t = threading.Thread(target=srv)
            t.start()
            c = client_ctx.wrap_socket(right)
            assert c.selected_alpn_protocol() == expect
            c.close()
            t.join(timeout=10)
        finally:
            left.close()
            right.close()


def test_h2_connection_churn_no_leak(h2_server):
    """100 short-lived h2 connections: every nghttp2 session, callback
    set, and stream state must be freed on connection_lost — the server's
    RSS must not grow materially with connection count."""
    base, img = h2_server

    def rss_mb():
        r = _curl(["-o", "-", base + "/health"])
        import json as _json

        return float(_json.loads(r.stdout)["allocatedMemoryMb"])

    # warm a few connections first so allocator pools settle
    for _ in range(10):
        _curl(["--http2", "-o", "/dev/null", base + "/health"])
    before = rss_mb()
    for _ in range(100):
        r = _curl(["--http2", "-o", "/dev/null", "-w", "%{http_code}",
                   base + "/health"])
        assert r.stdout == b"200"
    after = rss_mb()
    # 100 connections x (session + callbacks + buffers) would show up in
    # tens of MB if leaked; allow generous noise for GC timing
    assert after - before < 30.0, f"RSS grew {after - before:.1f} MB over 100 conns"
