"""Multi-chip sharded serving (ISSUE 15): per-chip batching lanes.

Pins the lane tier's contracts (engine/lanes.py + the executor's lane
loops):
  * placement — (queue depth x EWMA service time) scoring, device-frame-
    cache affinity with the imbalance fallback;
  * parity — mesh_policy="off" builds zero lane objects, adds zero new
    snapshot keys, and serves bytes identical to the direct chain;
  * routing — the sharded-dispatch profitability threshold and the
    oversize-single spatial route at the --spatial-mpix bar;
  * degraded mesh — drain-on-quarantine re-places every queued item onto
    survivors with the lane ledgers at rest afterwards, and the mesh
    generation (part of every sharded compile key) bumps exactly once
    per topology epoch so chip loss recompiles once, never per request;
  * prewarm — warm_mesh_paths covers the per-device and sharded compile
    keys, so compile_misses stays 0 across a run that loses a chip.
"""

import time
from concurrent.futures import Future

import numpy as np
import pytest

from imaginary_tpu import failpoints
from imaginary_tpu.engine import Executor, ExecutorConfig
from imaginary_tpu.engine import lanes as lanes_mod
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.ops import chain as chain_mod
from imaginary_tpu.ops.plan import plan_operation


def _img(h, w, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (h, w, 3), dtype=np.uint8)


def _resize_plan(h, w, width=48):
    return plan_operation("resize", ImageOptions(width=width), h, w, 0, 3)


class _FakeItem:
    """Placement-unit stand-in: place() reads .plan.frame_key and
    .future only (the ledger primitives read .lane)."""

    class _Plan:
        def __init__(self, fk):
            self.frame_key = fk

    def __init__(self, frame_key=None):
        self.plan = self._Plan(frame_key)
        self.future = Future()
        self.lane = None
        self.hops = 0


@pytest.fixture(autouse=True)
def _no_failpoints():
    yield
    failpoints.deactivate()


# -- placement (pure scheduler, no devices) ----------------------------------


class TestLanePlacement:
    def test_least_loaded_by_depth_times_ewma(self):
        fast = lanes_mod.Lane(0, None)
        slow = lanes_mod.Lane(1, None)
        fast.note_service(10.0)
        slow.note_service(100.0)
        # equal depth: the faster lane scores lower and wins
        sched = lanes_mod.LaneScheduler([fast, slow])
        assert sched.place(_FakeItem()) is fast
        # pile depth onto the fast lane until its (owed+1) x ewma crosses
        # the slow lane's: 11 x 10 > 1 x 100
        for _ in range(10):
            lanes_mod._lane_owe(fast, _FakeItem())
        assert sched.place(_FakeItem()) is slow

    def test_affinity_prefers_resident_lane(self):
        a, b = lanes_mod.Lane(0, None), lanes_mod.Lane(1, None)
        sched = lanes_mod.LaneScheduler([a, b])
        it1 = _FakeItem(frame_key="digest-1")
        first = sched.place(it1)
        lanes_mod._lane_owe(first, it1)  # mild load on the chosen lane
        # the repeat prefers the lane holding the resident frame even
        # though the other lane now scores (slightly) better
        again = sched.place(_FakeItem(frame_key="digest-1"))
        assert again is first
        assert first.affinity_hits >= 1

    def test_imbalance_falls_back_to_least_loaded(self):
        a, b = lanes_mod.Lane(0, None), lanes_mod.Lane(1, None)
        sched = lanes_mod.LaneScheduler([a, b], imbalance=2.0)
        it1 = _FakeItem(frame_key="digest-2")
        first = sched.place(it1)
        other = b if first is a else a
        # convoy the affine lane far past the imbalance bar
        for _ in range(20):
            lanes_mod._lane_owe(first, _FakeItem())
        chosen = sched.place(_FakeItem(frame_key="digest-2"))
        assert chosen is other
        assert other.affinity_misses >= 1
        # the affinity map re-learns: the NEXT repeat prefers the new lane
        assert sched.place(_FakeItem(frame_key="digest-2")) is other

    def test_quarantined_and_excluded_lanes_skipped(self):
        a, b = lanes_mod.Lane(0, None), lanes_mod.Lane(1, None)
        sched = lanes_mod.LaneScheduler([a, b])
        a.active = False
        assert sched.place(_FakeItem()) is b
        assert sched.place(_FakeItem(), exclude={1}) is None

    def test_owe_moves_charge_and_done_callback_refunds(self):
        a, b = lanes_mod.Lane(0, None), lanes_mod.Lane(1, None)
        it = _FakeItem()
        lanes_mod._lane_owe(a, it)
        assert (a.owed, b.owed) == (1, 0)
        lanes_mod._lane_owe(b, it)  # re-placement refunds the old owner
        assert (a.owed, b.owed) == (0, 1)
        it.future.set_result(None)  # resolution refunds whoever owns it
        assert (a.owed, b.owed) == (0, 0)
        assert it.lane is None


# -- parity: mesh_policy="off" ------------------------------------------------


class TestPolicyOffParity:
    def test_off_builds_no_lanes_and_serves_identical_bytes(self):
        arr = _img(96, 96, seed=3)
        plan = _resize_plan(96, 96)
        direct = chain_mod.run_batch([arr], [plan])[0]
        ex = Executor(ExecutorConfig(window_ms=1.0))
        try:
            assert ex._lanes is None
            out = ex.submit(arr, plan).result(timeout=60)
            np.testing.assert_array_equal(out, direct)
            d = ex.stats.to_dict()
            assert "lanes" not in d
            assert "mesh_generation" not in d
            assert "lanes" not in ex.debug_snapshot()
        finally:
            ex.shutdown()

    def test_lanes_serve_same_bytes_as_direct_chain(self):
        arr = _img(96, 96, seed=4)
        plan = _resize_plan(96, 96)
        direct = chain_mod.run_batch([arr], [plan])[0]
        ex = Executor(ExecutorConfig(mesh_policy="lanes", n_devices=4,
                                     window_ms=1.0))
        try:
            out = ex.submit(arr, plan).result(timeout=60)
            np.testing.assert_array_equal(out, direct)
        finally:
            ex.shutdown()


# -- routing ------------------------------------------------------------------


class TestShardedRouting:
    def _launch_spy(self, monkeypatch):
        calls = []
        real = chain_mod.launch_batch

        def spy(arrs, plans, sharding=None, device=None, device_cache=False):
            calls.append({"n": len(arrs), "sharding": sharding,
                          "device": device})
            return real(arrs, plans, sharding=sharding, device=device,
                        device_cache=device_cache)

        monkeypatch.setattr(chain_mod, "launch_batch", spy)
        return calls

    def test_below_threshold_rides_one_lane(self, monkeypatch):
        calls = self._launch_spy(monkeypatch)
        ex = Executor(ExecutorConfig(mesh_policy="sharded", n_devices=4,
                                     window_ms=2.0, shard_min_items=8))
        try:
            arr, plan = _img(96, 96), _resize_plan(96, 96)
            futs = [ex.submit(arr, plan) for _ in range(2)]
            [f.result(timeout=60) for f in futs]
        finally:
            ex.shutdown()
        assert calls and all(c["sharding"] is None and c["device"] is not None
                             for c in calls)

    def test_at_threshold_stages_sharded(self, monkeypatch):
        calls = self._launch_spy(monkeypatch)
        # placement spreads 16 arrivals over the 4 lanes (~4 each); with
        # the threshold at 2 every formed chunk crosses it and stages
        # sharded over the mesh
        ex = Executor(ExecutorConfig(mesh_policy="sharded", n_devices=4,
                                     window_ms=50.0, shard_min_items=2,
                                     max_batch=16))
        try:
            arr, plan = _img(96, 96), _resize_plan(96, 96)
            futs = [ex.submit(arr, plan) for _ in range(16)]
            [f.result(timeout=60) for f in futs]
        finally:
            ex.shutdown()
        sharded = [c for c in calls if c["sharding"] is not None]
        assert sharded
        assert all(c["n"] % 4 == 0 for c in sharded)  # mesh-axis multiple

    def test_spatial_route_at_mpix_bar(self):
        # (2, 2) mesh over 4 of the 8 virtual devices; the bucket for a
        # 512x512 single crosses a 0.2 Mpix bar and W splits evenly
        ex = Executor(ExecutorConfig(mesh_policy="lanes", n_devices=4,
                                     spatial=2, spatial_mpix=0.2,
                                     window_ms=1.0))
        try:
            assert ex.config.spatial_threshold_px == 200_000
            assert ex._spatial_sharding is not None
            arr, plan = _img(512, 512), _resize_plan(512, 512)
            out = ex.submit(arr, plan).result(timeout=120)
            assert out.shape[1] == 48
            assert ex.stats.spatial_batches == 1
            # a small single stays below the bar: no new spatial batch
            small, splan = _img(96, 96), _resize_plan(96, 96)
            ex.submit(small, splan).result(timeout=60)
            assert ex.stats.spatial_batches == 1
        finally:
            ex.shutdown()


# -- degraded mesh ------------------------------------------------------------


class TestDegradedMesh:
    def test_quarantine_drains_lane_and_ledgers_rest(self):
        ex = Executor(ExecutorConfig(mesh_policy="lanes", n_devices=4,
                                     window_ms=1.0, breaker_threshold=1,
                                     breaker_cooldown_s=300.0))
        try:
            arr, plan = _img(96, 96), _resize_plan(96, 96)
            [ex.submit(arr, plan).result(timeout=60) for _ in range(4)]
            gen0 = ex._mesh_generation
            failpoints.activate("device.chip_error[0]=error")
            futs = [ex.submit(arr, plan) for _ in range(24)]
            outs = [f.result(timeout=60) for f in futs]
            assert len(outs) == 24  # chip loss never costs availability
            failpoints.deactivate()
            deadline = time.monotonic() + 10.0
            lane0 = ex._lanes.lane(0)
            while lane0.active and time.monotonic() < deadline:
                time.sleep(0.02)
            assert not lane0.active
            # exactly one topology epoch for the single quarantine (the
            # compile-key pin: one recompile, not one per request)
            assert ex._mesh_generation - gen0 == 1
            # ledgers at rest: nothing owed or in flight anywhere
            for ln in ex._lanes.lanes:
                assert ln.owed == 0
                assert ln.inflight == 0
            snap = ex.stats.to_dict()
            assert [s["active"] for s in snap["lanes"]].count(False) == 1
        finally:
            ex.shutdown()

    def test_readmission_restores_lane_and_bumps_generation(self):
        # the cooldown must outlast the whole error storm: a shorter one
        # lets the half-open probe re-admit chip 0 MID-storm on a slow
        # host, fail again, and cycle twice (generation +4, not +2)
        ex = Executor(ExecutorConfig(mesh_policy="lanes", n_devices=4,
                                     window_ms=1.0, breaker_threshold=1,
                                     breaker_cooldown_s=3.0))
        try:
            arr, plan = _img(96, 96), _resize_plan(96, 96)
            [ex.submit(arr, plan).result(timeout=60) for _ in range(4)]
            gen0 = ex._mesh_generation
            failpoints.activate("device.chip_error[0]=error")
            futs = [ex.submit(arr, plan) for _ in range(8)]
            [f.result(timeout=60) for f in futs]
            failpoints.deactivate()
            lane0 = ex._lanes.lane(0)
            deadline = time.monotonic() + 15.0
            while not lane0.active and time.monotonic() < deadline:
                # keep light traffic flowing so collectors poll
                ex.submit(arr, plan).result(timeout=60)
                time.sleep(0.1)
            assert lane0.active  # the half-open probe re-admitted chip 0
            assert ex._mesh_generation - gen0 == 2  # out + back in
        finally:
            ex.shutdown()


# -- prewarm / compile-key pin ------------------------------------------------


class TestMeshGenerationCompileKeys:
    def test_generation_is_part_of_sharded_compile_key(self):
        from imaginary_tpu.parallel import batch_sharding, get_mesh

        mesh = get_mesh(4, 1, local=True)
        sh = batch_sharding(mesh)
        try:
            k0 = chain_mod._sharding_cache_key(sh)
            chain_mod.set_mesh_generation(chain_mod.mesh_generation() + 1)
            k1 = chain_mod._sharding_cache_key(sh)
            assert k0 != k1
            assert chain_mod._sharding_cache_key(None) is None
        finally:
            chain_mod.set_mesh_generation(0)

    @pytest.mark.slow
    def test_no_compile_misses_across_chip_loss(self):
        opts = ImageOptions(width=48)
        ex = Executor(ExecutorConfig(mesh_policy="lanes", n_devices=4,
                                     window_ms=1.0, breaker_threshold=1,
                                     breaker_cooldown_s=300.0))
        try:
            from imaginary_tpu.prewarm import warm_chain, warm_mesh_paths

            warm_chain("resize", opts, 96, 96, (1, 2, 4, 8, 16))
            warm_mesh_paths(ex, "resize", opts, 96, 96,
                            batch_sizes=(1, 2, 4, 8, 16))
            ex.stats.compile_misses = 0
            arr, plan = _img(96, 96), _resize_plan(96, 96)
            futs = [ex.submit(arr, plan) for _ in range(16)]
            [f.result(timeout=60) for f in futs]
            failpoints.activate("device.chip_error[0]=error")
            futs = [ex.submit(arr, plan) for _ in range(16)]
            [f.result(timeout=60) for f in futs]
            failpoints.deactivate()
            # survivors' per-device keys were prewarmed: chip loss moved
            # traffic without a single post-boot compile
            assert ex.stats.compile_misses == 0
        finally:
            ex.shutdown()


# -- observability surface ----------------------------------------------------


class TestLaneObservability:
    def test_stats_and_debug_snapshots(self):
        ex = Executor(ExecutorConfig(mesh_policy="lanes", n_devices=4,
                                     window_ms=1.0))
        try:
            arr, plan = _img(96, 96), _resize_plan(96, 96)
            futs = [ex.submit(arr, plan) for _ in range(8)]
            [f.result(timeout=60) for f in futs]
            d = ex.stats.to_dict()
            assert len(d["lanes"]) == 4
            for s in d["lanes"]:
                for k in ("lane", "active", "queued", "inflight",
                          "dispatches", "ewma_ms", "affinity_hit_ratio"):
                    assert k in s
            assert sum(s["dispatches"] for s in d["lanes"]) >= 1
            dz = ex.debug_snapshot()["lanes"]
            assert dz["policy"] == "lanes"
            assert "stage_times" in dz and "mesh_generation" in dz
            # devhealth snapshot carries the same per-lane block (/health)
            dh = ex.devhealth.snapshot()
            assert len(dh["lanes"]) == 4
        finally:
            ex.shutdown()

    def test_wire_bytes_attributed_per_device(self):
        from imaginary_tpu.engine.timing import WIRE

        WIRE.reset()
        ex = Executor(ExecutorConfig(mesh_policy="lanes", n_devices=4,
                                     window_ms=1.0))
        try:
            arr, plan = _img(96, 96), _resize_plan(96, 96)
            futs = [ex.submit(arr, plan) for _ in range(8)]
            [f.result(timeout=60) for f in futs]
            d = ex.stats.to_dict()
            assert "wire_bytes_by_device" in d
            assert d["wire_bytes_by_device"]["h2d"]  # per-chip H2D booked
        finally:
            ex.shutdown()
            WIRE.reset()
