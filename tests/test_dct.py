"""Compressed-domain ingest (--transport-dct) tests.

Covers the ISSUE 14 surface: golden decode parity against libjpeg's own
scaled decode (PIL draft mode) at every shrink-on-load fraction, the
odd-dimension / edge-block cases, off-by-default byte parity, the
u8/int16 staging tripwire (no float ever crosses the link), the
device-resident frame cache + pressure governor integration, and the
wire-bytes ledger surfaces on /health //metrics //debugz.

Parity basis: the packed transport replays libjpeg's reduced-size IDCT
exactly — the k-point fold carries jidctred's per-frequency cosine
weights and 4:2:0 chroma folds at 2k (libjpeg scales subsampled
components at twice the luma factor, landing them at output resolution
with no upsample). Measured corpus residual is <= 3 grey levels; the
assertions below leave a small margin but stay far inside the dual
integrity tolerance (max 96 / mean 16, engine/integrity.py).
"""

import asyncio
import hashlib
import io

import numpy as np
import pytest
from PIL import Image

from imaginary_tpu import pipeline
from imaginary_tpu.cache import CacheSet, DeviceFrameCache, FrameCache
from imaginary_tpu.codecs import jpeg_dct
from imaginary_tpu.engine.timing import WIRE
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.ops import chain as chain_mod
from imaginary_tpu.ops.buckets import dct_packed_geometry
from imaginary_tpu.ops.plan import (
    ImagePlan,
    StageInstance,
    plan_operation,
    wrap_plan_dct,
)
from imaginary_tpu.ops.stages import FromDctSpec
from tests.conftest import fixture_bytes

CORPUS = ["imaginary.jpg", "medium.jpg", "large.jpg", "smart-crop.jpg",
          "exif-orient-6.jpg"]
SHRINKS = [1, 2, 4, 8]


@pytest.fixture(autouse=True)
def _reset_transport(testdata):
    yield
    pipeline.set_transport_dct(False)
    chain_mod.set_device_frame_cache(None)


_COEFF_CACHE: dict = {}


def _coefficients(name_or_buf):
    """Entropy decode is the slow pure-Python stage — cache per source."""
    if isinstance(name_or_buf, str):
        key, buf = name_or_buf, fixture_bytes(name_or_buf)
    else:
        buf = name_or_buf
        key = hashlib.sha256(buf).hexdigest()
    if key not in _COEFF_CACHE:
        _COEFF_CACHE[key] = jpeg_dct.decode_coefficients(buf)
    return _COEFF_CACHE[key]


def _pil_draft_rgb(buf: bytes, shrink: int) -> np.ndarray:
    """libjpeg's own scaled decode (the ground truth the transport must
    reproduce): draft mode selects the same 1/shrink reduced IDCT."""
    im = Image.open(io.BytesIO(buf))
    if shrink > 1:
        im.draft("RGB", (im.width // shrink, im.height // shrink))
    return np.asarray(im.convert("RGB"))


def _device_decode_rgb(coeffs, shrink: int) -> np.ndarray:
    """Run ONLY the decode leg of the transport — pack_dct on the host,
    FromDctSpec (IDCT + upsample + color convert) on the device — through
    the real chain, returning full-resolution-at-scale RGB."""
    packed = jpeg_dct.pack_dct(coeffs, shrink)
    k, h2, w2, hb, wb = dct_packed_geometry(coeffs.h, coeffs.w, shrink)
    plan = ImagePlan(
        stages=[StageInstance(FromDctSpec(hb, wb, k), {})],
        out_h=h2, out_w=w2, transport="rgb",
        in_bucket=(hb + hb // 2, wb) if shrink == 1 else (hb, wb),
        in_h=h2, in_w=w2, out_bucket=(hb, wb),
    )
    return np.asarray(chain_mod.run_single(packed, plan))


class TestDecodeParity:
    @pytest.mark.parametrize("name", CORPUS)
    @pytest.mark.parametrize("shrink", SHRINKS)
    def test_corpus_parity_vs_libjpeg(self, name, shrink):
        buf = fixture_bytes(name)
        c = _coefficients(name)
        assert c is not None, f"{name} should be in decoder scope"
        got = _device_decode_rgb(c, shrink)
        ref = _pil_draft_rgb(buf, shrink)
        assert got.shape == ref.shape
        d = np.abs(got.astype(np.int16) - ref.astype(np.int16))
        # measured corpus-wide residual is <= 3 (libjpeg's fixed-point
        # color convert); the dual integrity tolerance is 96 / 16
        assert int(d.max()) <= 8, f"{name} 1/{shrink}: max {int(d.max())}"
        assert float(d.mean()) <= 2.0, f"{name} 1/{shrink}: mean {d.mean():.2f}"

    @pytest.mark.parametrize("shrink", SHRINKS)
    def test_odd_dimensions_edge_blocks(self, shrink):
        # 117x203: both dims odd, neither a multiple of the 16x16 MCU —
        # exercises the partial edge blocks and the ceil() geometry at
        # every fold factor
        rng = np.random.default_rng(7)
        base = rng.integers(0, 256, (117, 203, 3), dtype=np.uint8)
        # smooth it: random noise is the decoder's worst case for
        # quantization error masking real geometry bugs
        im = Image.fromarray(base).resize((203, 117), Image.BILINEAR)
        b = io.BytesIO()
        im.save(b, "JPEG", quality=92, subsampling=2)
        buf = b.getvalue()
        c = _coefficients(buf)
        assert c is not None
        assert (c.h, c.w) == (117, 203)
        got = _device_decode_rgb(c, shrink)
        ref = _pil_draft_rgb(buf, shrink)
        assert got.shape == ref.shape
        d = np.abs(got.astype(np.int16) - ref.astype(np.int16))
        assert int(d.max()) <= 8 and float(d.mean()) <= 2.0

    def test_out_of_scope_streams_bail(self):
        # progressive JPEG: in-scope subsampling but SOF2 — the decoder
        # must return None (runtime fallback to yuv/rgb), never garbage
        im = Image.open(io.BytesIO(fixture_bytes("medium.jpg"))).convert("RGB")
        b = io.BytesIO()
        im.save(b, "JPEG", quality=85, subsampling=2, progressive=True)
        assert jpeg_dct.decode_packed(b.getvalue(), 1) is None
        # 4:4:4 joined the decoder's scope (gray/444/422/420 all ride);
        # verify it decodes and self-identifies
        b2 = io.BytesIO()
        im.save(b2, "JPEG", quality=85, subsampling=0)
        got = jpeg_dct.decode_packed(b2.getvalue(), 1)
        assert got is not None
        assert got[3] == "444"
        # arithmetic-coded and CMYK streams stay out of scope
        b3 = io.BytesIO()
        im.convert("CMYK").save(b3, "JPEG", quality=85)
        assert jpeg_dct.decode_packed(b3.getvalue(), 1) is None


class TestEndToEnd:
    def test_resize_parity_on_vs_off(self):
        buf = fixture_bytes("medium.jpg")
        o = ImageOptions(width=160)
        pipeline.set_transport_dct(False)
        off = pipeline.process_operation("resize", buf, o)
        pipeline.set_transport_dct(True)
        on = pipeline.process_operation("resize", buf, o)
        assert on.mime == off.mime == "image/jpeg"
        a = np.asarray(Image.open(io.BytesIO(off.body)).convert("RGB"))
        b = np.asarray(Image.open(io.BytesIO(on.body)).convert("RGB"))
        assert a.shape == b.shape
        from imaginary_tpu.engine.integrity import outputs_match

        assert outputs_match(b, a, exact=False)

    def test_thumbnail_deep_shrink_parity(self):
        # thumbnail on a 1080p-class source picks the deepest fold
        buf = fixture_bytes("large.jpg")
        o = ImageOptions(width=100)
        pipeline.set_transport_dct(False)
        off = pipeline.process_operation("thumbnail", buf, o)
        pipeline.set_transport_dct(True)
        on = pipeline.process_operation("thumbnail", buf, o)
        a = np.asarray(Image.open(io.BytesIO(off.body)).convert("RGB"))
        b = np.asarray(Image.open(io.BytesIO(on.body)).convert("RGB"))
        assert a.shape == b.shape
        from imaginary_tpu.engine.integrity import outputs_match

        assert outputs_match(b, a, exact=False)

    def test_pipeline_endpoint_rides_dct(self):
        from imaginary_tpu.options import PipelineOperation

        buf = fixture_bytes("medium.jpg")
        ops = [PipelineOperation(name="resize", params={"width": 200}),
               PipelineOperation(name="crop",
                                 params={"width": 120, "height": 90})]
        o = ImageOptions(operations=ops)
        pipeline.set_transport_dct(False)
        off = pipeline.process_pipeline(buf, o)
        pipeline.set_transport_dct(True)
        on = pipeline.process_pipeline(buf, o)
        a = np.asarray(Image.open(io.BytesIO(off.body)).convert("RGB"))
        b = np.asarray(Image.open(io.BytesIO(on.body)).convert("RGB"))
        assert a.shape == b.shape == (90, 120, 3)
        from imaginary_tpu.engine.integrity import outputs_match

        assert outputs_match(b, a, exact=False)

    def test_non_jpeg_output_stays_off_transport(self, monkeypatch):
        pipeline.set_transport_dct(True)
        monkeypatch.setattr(
            jpeg_dct, "decode_packed",
            lambda *_a, **_k: pytest.fail("dct decode consulted for png out"))
        out = pipeline.process_operation(
            "resize", fixture_bytes("medium.jpg"),
            ImageOptions(width=100, type="png"))
        assert out.mime == "image/png"


class TestOffByDefault:
    def test_switch_defaults_off_everywhere(self):
        assert pipeline.transport_dct_enabled() is False
        from imaginary_tpu.web.config import ServerOptions

        o = ServerOptions()
        assert o.transport_dct is False
        assert o.cache_device_mb == 0.0

    def test_off_state_never_consults_decoder(self, monkeypatch):
        # byte parity pin: with the flag off the dct module is never even
        # consulted, so responses are bit-for-bit the pre-transport build's
        monkeypatch.setattr(
            jpeg_dct, "decode_packed",
            lambda *_a, **_k: pytest.fail("dct decode ran with switch off"))
        out = pipeline.process_operation(
            "resize", fixture_bytes("medium.jpg"), ImageOptions(width=100))
        assert out.mime == "image/jpeg"

    def test_off_state_responses_deterministic(self):
        buf = fixture_bytes("imaginary.jpg")
        o = ImageOptions(width=120)
        a = pipeline.process_operation("resize", buf, o)
        b = pipeline.process_operation("resize", buf, o)
        assert a.body == b.body


class TestStagingTripwire:
    def test_no_float_ever_staged_h2d(self, monkeypatch):
        """Across every launch_batch transport the staged H2D batch
        operand is u8 (rgb, yuv420) or int16 (dct) — a float32 operand
        would 4x the wire bytes and silently void the transport's reason
        to exist. Per-plan dyn parameters (a handful of f32 scalars per
        stage) are exempt: the tripwire watches anything big enough to be
        pixel data, not the few-byte argument vectors."""
        import jax

        staged = []
        real = jax.device_put

        def spy(x, *a, **k):
            dt = getattr(x, "dtype", None)
            if dt is not None and getattr(x, "size", 0) >= 4096:
                staged.append(np.dtype(dt))
            return real(x, *a, **k)

        monkeypatch.setattr(jax, "device_put", spy)
        buf = fixture_bytes("medium.jpg")
        c = _coefficients("medium.jpg")

        # rgb transport
        arr = np.asarray(Image.open(io.BytesIO(buf)).convert("RGB"))
        plan = plan_operation("resize", ImageOptions(width=64),
                              arr.shape[0], arr.shape[1], 0, 3)
        staged.clear()
        chain_mod.run_batch([arr, arr], [plan, plan])
        assert staged, "expected at least one staged transfer"
        bad = [d for d in staged if d.kind == "f"]
        assert not bad, f"float operand staged on rgb path: {bad}"

        # dct transport, folded and full-scale layouts
        for shrink in (1, 4):
            packed = jpeg_dct.pack_dct(c, shrink)
            _, h2, w2, _, _ = dct_packed_geometry(c.h, c.w, shrink)
            p = plan_operation("resize", ImageOptions(width=64), h2, w2, 0, 3)
            wrapped = wrap_plan_dct(p, c.h, c.w, shrink)
            staged.clear()
            chain_mod.run_batch([packed, packed], [wrapped, wrapped])
            assert staged
            bad = [d for d in staged if d.kind == "f"]
            assert not bad, f"float operand staged on dct path: {bad}"
            assert np.dtype(np.int16) in staged

    def test_packed_buffer_is_int16(self):
        c = _coefficients("imaginary.jpg")
        for shrink in SHRINKS:
            assert jpeg_dct.pack_dct(c, shrink).dtype == np.int16


class TestDeviceFrameCache:
    def _serve_twice(self, cs):
        dc = DeviceFrameCache(cs.device, cs.stats)
        chain_mod.set_device_frame_cache(dc)
        fc = FrameCache(cs.frames, cs.stats)
        pipeline.set_transport_dct(True)
        buf = fixture_bytes("medium.jpg")
        digest = hashlib.sha256(buf).hexdigest()
        o = ImageOptions(width=100)
        w0 = WIRE.snapshot()
        r1 = pipeline.process_operation("resize", buf, o,
                                        frame_cache=fc, source_digest=digest)
        w1 = WIRE.snapshot()
        r2 = pipeline.process_operation("resize", buf, o,
                                        frame_cache=fc, source_digest=digest)
        w2 = WIRE.snapshot()
        assert r1.body == r2.body
        return dc, (w0, w1, w2)

    def test_hot_source_pays_zero_h2d(self):
        cs = CacheSet(frame_mb=8.0, device_mb=8.0)
        dc, (w0, w1, w2) = self._serve_twice(cs)
        assert w1["h2d"] > w0["h2d"]  # first request staged the input
        assert w2["h2d"] == w1["h2d"]  # repeat request: zero H2D
        assert w2["d2h"] > w1["d2h"]  # the result still drains
        assert cs.stats.device_misses == 1 and cs.stats.device_hits == 1
        assert dc.bytes_used > 0
        assert cs.to_dict()["device_bytes"] == dc.bytes_used

    def test_pressure_ladder_shrinks_then_disables(self):
        cs = CacheSet(frame_mb=8.0, device_mb=8.0)
        dc, _ = self._serve_twice(cs)
        base = cs.device.budget
        assert base == int(8.0 * 1e6)
        cs.apply_pressure(1)  # elevated: halve
        assert cs.device.budget == base // 2
        assert dc.enabled
        cs.apply_pressure(2)  # critical: disable + flush (HBM goes back)
        assert not dc.enabled
        assert dc.bytes_used == 0 and len(dc) == 0
        # disabled cache: serving continues, inputs just re-stage
        w_before = WIRE.snapshot()["h2d"]
        buf = fixture_bytes("medium.jpg")
        digest = hashlib.sha256(buf).hexdigest()
        fc = FrameCache(cs.frames, cs.stats)
        pipeline.process_operation("resize", buf, ImageOptions(width=100),
                                   frame_cache=fc, source_digest=digest)
        assert WIRE.snapshot()["h2d"] > w_before
        cs.apply_pressure(0)  # recovery: budget restored
        assert cs.device.budget == base and dc.enabled

    def test_no_digest_no_device_caching(self):
        cs = CacheSet(device_mb=8.0)
        dc = DeviceFrameCache(cs.device, cs.stats)
        chain_mod.set_device_frame_cache(dc)
        pipeline.set_transport_dct(True)
        pipeline.process_operation("resize", fixture_bytes("medium.jpg"),
                                   ImageOptions(width=100))
        # without a content digest there is no stable identity to pin
        assert len(dc) == 0 and cs.stats.device_hits == 0


class TestHttpSurfaces:
    def test_health_metrics_debugz_carry_device_and_wire(self):
        from aiohttp.test_utils import TestClient, TestServer

        from imaginary_tpu.web.app import create_app
        from imaginary_tpu.web.config import ServerOptions

        opts = ServerOptions(transport_dct=True, cache_frame_mb=8.0,
                             cache_device_mb=8.0, enable_debug=True)

        async def runner():
            app = create_app(opts, log_stream=io.StringIO())
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                body = fixture_bytes("medium.jpg")
                for _ in range(2):
                    res = await client.post(
                        "/resize?width=100", data=body,
                        headers={"Content-Type": "image/jpeg"})
                    assert res.status == 200
                h = await (await client.get("/health")).json()
                # the device frame key carries the placement's device
                # descriptor, so a repeat that lands on a DIFFERENT chip
                # misses (placement shifts with load EWMAs); keep posting
                # the identical request until one lands where the frame
                # is resident — the wiring, not the placement, is under
                # test here
                for _ in range(6):
                    if h["cache"]["device_hits"] >= 1:
                        break
                    res = await client.post(
                        "/resize?width=100", data=body,
                        headers={"Content-Type": "image/jpeg"})
                    assert res.status == 200
                    h = await (await client.get("/health")).json()
                assert h["cache"]["device_bytes"] > 0
                assert h["cache"]["device_hits"] >= 1
                assert h["executor"]["wire_bytes"]["d2h"] > 0
                m = await (await client.get("/metrics")).text()
                assert 'imaginary_tpu_wire_bytes_total{direction="h2d"}' in m
                assert 'imaginary_tpu_wire_transfers_total{direction="d2h"}' in m
                assert "imaginary_tpu_cache_device_bytes" in m
                d = await (await client.get("/debugz")).json()
                assert d["cache"]["device_bytes"] > 0
            finally:
                await client.close()

        asyncio.run(runner())


class TestPrewarmCoverage:
    def test_compile_misses_zero_after_warm(self):
        from imaginary_tpu import prewarm
        from imaginary_tpu.engine.executor import Executor, ExecutorConfig

        pipeline.set_transport_dct(True)
        # smallest corpus source (300x400) so the warm stays cheap
        src_h, src_w = 300, 400
        o = ImageOptions(width=120)
        built = prewarm.warm_chain("resize", o, src_h, src_w, (1,))
        assert built >= 2  # at least the rgb and dct programs
        c = _coefficients("exif-orient-6.jpg")
        from imaginary_tpu.ops.plan import choose_decode_shrink

        shrink = choose_decode_shrink("resize", o, src_h, src_w, 0, 3)
        packed = jpeg_dct.pack_dct(c, shrink)
        _, h2, w2, _, _ = dct_packed_geometry(c.h, c.w, shrink)
        plan = plan_operation("resize", o, h2, w2, 0, 3)
        wrapped = wrap_plan_dct(plan, c.h, c.w, shrink)
        ex = Executor(ExecutorConfig())
        try:
            ex.process(packed, wrapped)
            assert ex.stats.to_dict()["compile_misses"] == 0
        finally:
            ex.shutdown()
