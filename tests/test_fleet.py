"""Fleet tier: crash-safe shared result cache, worker fencing, and the
ingress read guard (ISSUE 11).

The shm protocol tests drive the real mmap file — torn writes come from
a genuinely SIGKILLed subprocess (slow-marked) and from direct state
surgery (fast); corruption is a real flipped byte under a sealed
checksum. The HTTP tests pin the tiered-lookup contract: shm-hit bytes
identical to local-hit bytes, fleet-off byte parity, and the /health
/metrics /debugz surfaces. The supervisor-side fencing/roll transitions
live in tests/test_workers.py; the full process-kill story is the
`make chaos` fleet rows (bench_chaos.py).
"""

import asyncio
import hashlib
import io
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from imaginary_tpu import cache as cache_mod
from imaginary_tpu import failpoints
from imaginary_tpu.fleet import shmcache
from imaginary_tpu.fleet.shmcache import (
    FREE,
    SEALED,
    WRITING,
    ShmCache,
)
from imaginary_tpu.web.config import ServerOptions
from tests.conftest import fixture_bytes

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _fixtures(testdata):
    return testdata


@pytest.fixture()
def shm(tmp_path):
    path = str(tmp_path / "fleet.shm")
    sup = ShmCache(path, create=True, size_mb=2.0, owner=True)
    worker = ShmCache(path, create=False, worker=0, epoch=0)
    yield sup, worker
    worker.close()
    sup.close()


def _key(tag: bytes) -> bytes:
    return hashlib.sha256(tag).digest()


# --- shm protocol ------------------------------------------------------------


class TestShmCache:
    def test_roundtrip_and_counters(self, shm):
        _, w = shm
        k = _key(b"a")
        assert w.get(k) is None
        assert w.stats.misses == 1
        assert w.put(k, b"image/jpeg\ndevice", b"B" * 1000)
        assert w.get(k) == (b"image/jpeg\ndevice", b"B" * 1000)
        assert w.stats.hits == 1 and w.stats.publishes == 1

    def test_cross_process_attach_sees_entries(self, shm, tmp_path):
        _, w = shm
        k = _key(b"shared")
        w.put(k, b"m", b"payload")
        sibling = ShmCache(w.path, create=False, worker=1, epoch=0)
        try:
            assert sibling.get(k) == (b"m", b"payload")
        finally:
            sibling.close()

    def test_oversize_entry_refused(self, shm):
        _, w = shm
        assert not w.put(_key(b"big"), b"m", b"x" * shmcache.SLOT_BYTES)
        assert w.stats.publish_oversize == 1

    def test_attach_rejects_non_cache_file(self, tmp_path):
        bogus = tmp_path / "bogus.shm"
        bogus.write_bytes(b"\x00" * 8192)
        with pytest.raises(ValueError):
            ShmCache(str(bogus), create=False)

    def test_fencing_blocks_publish_not_read(self, shm):
        sup, w = shm
        k = _key(b"f")
        assert w.put(k, b"m", b"body")
        sup.stamp_epoch(0, 7)  # a successor for index 0 was stamped
        assert w.fenced()
        assert not w.put(_key(b"f2"), b"m", b"body2")
        assert w.stats.fenced_publishes == 1
        # the deposed worker may still READ (immutable sealed entries)
        assert w.get(k) == (b"m", b"body")
        sup.stamp_epoch(0, 0)
        assert not w.fenced()

    def test_zombie_failpoint_forces_fenced_path(self, shm):
        _, w = shm
        failpoints.activate("worker.zombie=error")
        try:
            assert not w.put(_key(b"z"), b"m", b"b")
            assert w.stats.fenced_publishes == 1
        finally:
            failpoints.deactivate()

    def test_checksum_corruption_reads_as_miss_and_reclaims(self, shm):
        _, w = shm
        k = _key(b"c")
        w.put(k, b"m", b"D" * 256)
        idx = w._candidates(k)[0]
        off = w._slot_off(idx) + shmcache._SLOT_DATA_OFF + 10
        w._mm[off] ^= 0x80  # one flipped bit under a sealed checksum
        assert w.get(k) is None  # corrupt bytes are NEVER returned
        assert w.stats.corrupt == 1
        assert w.stats.corrupt_served == 0  # the tripwire stays zero
        assert w._slot_state(idx) == FREE  # reclaimed for reuse

    def test_write_failpoint_error_abandons_cleanly(self, shm):
        _, w = shm
        k = _key(b"e")
        failpoints.activate("fleet.write=error")
        try:
            assert not w.put(k, b"m", b"b")
        finally:
            failpoints.deactivate()
        # deliberate abandon resets FREE immediately (only writer DEATH
        # leaves WRITING behind); slot is reusable right away
        assert w._slot_state(w._candidates(k)[0]) == FREE
        assert w.put(k, b"m", b"b") and w.get(k) == (b"m", b"b")

    def test_torn_slot_skipped_and_swept(self, shm):
        _, w = shm
        k = _key(b"t")
        w.put(k, b"m", b"body")
        idx = w._candidates(k)[0]
        # surgical torn write: WRITING state with no live lock holder,
        # exactly what a SIGKILLed writer leaves (the subprocess variant
        # below proves the real thing; this one keeps the tier-1 run fast)
        import struct

        struct.pack_into("<I", w._mm, w._slot_off(idx), WRITING)
        assert w.get(k) is None  # readers skip unpublished slots
        assert w.sweep() == 1
        assert w._slot_state(idx) == FREE

    def test_eviction_prefers_oldest_tick(self, shm):
        _, w = shm
        for i in range(w.nslots * 12):
            w.put(_key(b"fill%d" % i), b"m", b"y" * 200)
        scan = w.slot_scan()
        assert scan["sealed"] <= w.nslots
        assert w.stats.evictions > 0

    def test_epoch_table_bounds(self, shm):
        sup, _ = shm
        sup.stamp_epoch(shmcache.MAX_WORKERS + 5, 9)  # clamped, no crash
        assert sup.epoch_of(shmcache.MAX_WORKERS - 1) == 9

    def test_snapshot_surfaces(self, shm):
        _, w = shm
        w.put(_key(b"s"), b"m", b"b")
        snap = w.snapshot()
        for field in ("worker", "epoch", "fenced", "slots", "sealed",
                      "hits", "misses", "publishes", "corrupt",
                      "corrupt_served", "torn_reclaimed"):
            assert field in snap
        dbg = w.debug_snapshot()
        assert dbg["path"] == w.path and "epochs" in dbg

    def test_shared_key_matches_etag_derivation(self):
        key = (hashlib.sha256(b"src").digest(), "resize", ("w", 300))
        assert cache_mod.strong_etag(key) == \
            '"' + cache_mod.shared_key(key).hex()[:32] + '"'

    @pytest.mark.slow
    def test_sigkilled_writer_leaves_reclaimable_torn_slot(self, tmp_path):
        path = str(tmp_path / "torn.shm")
        sup = ShmCache(path, create=True, size_mb=1.0, owner=True)
        code = (
            "import hashlib\n"
            "from imaginary_tpu import failpoints\n"
            "from imaginary_tpu.fleet.shmcache import ShmCache\n"
            "failpoints.activate('fleet.write=delay(30s)')\n"
            f"w = ShmCache({path!r}, create=False, worker=1, epoch=0)\n"
            "print('mid-write', flush=True)\n"
            "w.put(hashlib.sha256(b'torn').digest(), b'm', b'x' * 500)\n"
        )
        p = subprocess.Popen([sys.executable, "-c", code], cwd=ROOT,
                             stdout=subprocess.PIPE)
        try:
            assert b"mid-write" in p.stdout.readline()
            time.sleep(1.0)  # the deposit is inside the WRITING window
            p.kill()
            p.wait()
            k = hashlib.sha256(b"torn").digest()
            idx = sup._candidates(k)[0]
            assert sup._slot_state(idx) == WRITING
            assert sup.get(k) is None  # skipped, not served half-written
            assert sup.sweep() == 1  # kernel released the dead lock
            assert sup._slot_state(idx) == FREE
        finally:
            if p.poll() is None:
                p.kill()
                p.wait()
            sup.close()


# --- the tiered HTTP path ----------------------------------------------------


def run(options, fn):
    """test_cache.py's harness: run fn(client, app) on a fresh app."""

    async def runner():
        from imaginary_tpu.web.app import create_app

        app = create_app(options, log_stream=io.StringIO())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await fn(client, app)
        finally:
            await client.close()

    asyncio.run(runner())


def jpg() -> bytes:
    return fixture_bytes("imaginary.jpg")


def _post_kw():
    return {"data": jpg(), "headers": {"Content-Type": "image/jpeg"}}


class TestTieredLookup:
    def test_shm_hit_bytes_identical_to_local_hit(self, tmp_path):
        os.environ.pop(shmcache.PATH_ENV, None)

        async def fn(client, app):
            svc = app["service"]
            r1 = await client.post("/resize?width=120&height=90", **_post_kw())
            b1 = await r1.read()
            assert r1.status == 200
            r2 = await client.post("/resize?width=120&height=90", **_post_kw())
            assert await r2.read() == b1  # local hit
            svc.caches.result.clear()
            r3 = await client.post("/resize?width=120&height=90", **_post_kw())
            assert await r3.read() == b1  # shm hit: byte-identical
            assert r3.headers.get("X-Imaginary-Backend") == \
                r1.headers.get("X-Imaginary-Backend")
            assert r3.headers.get("ETag") == r1.headers.get("ETag")
            assert svc.caches.shm.stats.hits == 1

        run(ServerOptions(fleet_cache_mb=4.0, cache_result_mb=4.0), fn)

    def test_shm_tier_works_without_local_result_cache(self):
        os.environ.pop(shmcache.PATH_ENV, None)

        async def fn(client, app):
            svc = app["service"]
            r1 = await client.post("/resize?width=100", **_post_kw())
            b1 = await r1.read()
            assert r1.status == 200 and svc.caches.shm.stats.publishes == 1
            r2 = await client.post("/resize?width=100", **_post_kw())
            assert await r2.read() == b1
            assert svc.caches.shm.stats.hits == 1
            # the shm tier carries the strong ETag/304 contract alone
            etag = r1.headers.get("ETag")
            assert etag
            r3 = await client.post("/resize?width=100", data=jpg(), headers={
                "Content-Type": "image/jpeg", "If-None-Match": etag})
            assert r3.status == 200  # POST never 304s; GET does below

        run(ServerOptions(fleet_cache_mb=4.0), fn)

    def test_fleet_off_byte_parity(self):
        os.environ.pop(shmcache.PATH_ENV, None)
        bodies = {}

        async def baseline(client, app):
            r = await client.post("/resize?width=140&height=100", **_post_kw())
            bodies["off"] = await r.read()
            assert app["service"].caches.shm is None
            h = await client.get("/health")
            assert "fleet" not in await h.json()

        async def armed(client, app):
            r = await client.post("/resize?width=140&height=100", **_post_kw())
            bodies["on"] = await r.read()

        run(ServerOptions(), baseline)
        run(ServerOptions(fleet_cache_mb=4.0), armed)
        assert bodies["off"] == bodies["on"]

    def test_fenced_worker_serves_but_does_not_publish(self):
        os.environ.pop(shmcache.PATH_ENV, None)

        async def fn(client, app):
            svc = app["service"]
            svc.caches.shm.stamp_epoch(0, 99)  # depose worker 0
            r = await client.post("/resize?width=90", **_post_kw())
            assert r.status == 200  # serving is unaffected
            assert svc.caches.shm.stats.fenced_publishes == 1
            assert svc.caches.shm.stats.publishes == 0
            h = await (await client.get("/health")).json()
            assert h["fleet"]["fenced"] is True

        run(ServerOptions(fleet_cache_mb=4.0), fn)

    def test_fleet_write_fault_degrades_to_uncached_success(self):
        os.environ.pop(shmcache.PATH_ENV, None)

        async def fn(client, app):
            failpoints.activate("fleet.write=error")
            try:
                r = await client.post("/resize?width=80", **_post_kw())
                assert r.status == 200  # a broken deposit costs a miss only
            finally:
                failpoints.deactivate()
            assert app["service"].caches.shm.stats.publishes == 0

        run(ServerOptions(fleet_cache_mb=4.0), fn)

    def test_health_metrics_debugz_fleet_blocks(self):
        os.environ.pop(shmcache.PATH_ENV, None)

        async def fn(client, app):
            await client.post("/resize?width=70", **_post_kw())
            h = await (await client.get("/health")).json()
            assert h["epoch"] == 0
            fleet = h["fleet"]
            assert fleet["publishes"] == 1 and fleet["sealed"] == 1
            m = await (await client.get("/metrics")).text()
            assert "imaginary_tpu_fleet_cache_publishes_total 1" in m
            assert "imaginary_tpu_fleet_cache_corrupt_served_total 0" in m
            assert "imaginary_tpu_fleet_epoch 0" in m
            d = await (await client.get("/debugz")).json()
            assert d["fleet"]["path"] == app["service"].caches.shm.path

        run(ServerOptions(fleet_cache_mb=4.0, enable_debug=True), fn)

    def test_corrupt_shared_entry_recomputed_not_served(self):
        os.environ.pop(shmcache.PATH_ENV, None)

        async def fn(client, app):
            svc = app["service"]
            r1 = await client.post("/resize?width=60", **_post_kw())
            b1 = await r1.read()
            # scribble on the sealed entry, then force a shm lookup
            shm = svc.caches.shm
            for idx in range(shm.nslots):
                if shm._slot_state(idx) == SEALED:
                    shm._mm[shm._slot_off(idx) + shmcache._SLOT_DATA_OFF
                            + 24] ^= 0xFF
            svc.caches.result.clear()
            r2 = await client.post("/resize?width=60", **_post_kw())
            b2 = await r2.read()
            assert r2.status == 200 and b2 == b1  # recomputed, identical
            assert shm.stats.corrupt >= 1
            assert shm.stats.corrupt_served == 0

        run(ServerOptions(fleet_cache_mb=4.0, cache_result_mb=4.0), fn)


# --- ingress read guard ------------------------------------------------------


class _Echo(asyncio.Protocol):
    """Minimal inner protocol: answers any complete request-ish blob."""

    def connection_made(self, transport):
        self.transport = transport

    def data_received(self, data):
        pass

    def connection_lost(self, exc):
        pass

    def eof_received(self):
        return False


class TestReadTimeoutGuard:
    def _serve(self, timeout_s):
        from imaginary_tpu.web.ingress import IngressStats, ReadTimeoutGuard

        stats = IngressStats()

        async def start():
            loop = asyncio.get_running_loop()
            server = await loop.create_server(
                lambda: ReadTimeoutGuard(_Echo(), timeout_s, stats=stats),
                "127.0.0.1", 0)
            return server, server.sockets[0].getsockname()[1]

        return stats, start

    def test_stalled_header_read_is_closed(self):
        stats, start = self._serve(0.3)

        async def fn():
            server, port = await start()
            try:
                r, w = await asyncio.open_connection("127.0.0.1", port)
                w.write(b"POST /resize HTTP/1.1\r\nHost: x\r\n")  # never finishes
                await w.drain()
                got = await asyncio.wait_for(r.read(), timeout=3.0)
                assert got == b""  # server closed on us
            finally:
                server.close()
                await server.wait_closed()

        asyncio.run(fn())
        assert stats.read_timeouts == 1

    def test_flowing_slow_body_survives(self):
        stats, start = self._serve(0.4)

        async def fn():
            server, port = await start()
            try:
                r, w = await asyncio.open_connection("127.0.0.1", port)
                w.write(b"POST /x HTTP/1.1\r\nHost: x\r\n"
                        b"Content-Length: 50\r\n\r\n")
                await w.drain()
                for _ in range(10):  # 50 bytes trickled under the deadline
                    w.write(b"AAAAA")
                    await w.drain()
                    await asyncio.sleep(0.1)
                # body complete -> IDLE: the guard must now leave the
                # connection alone even well past the timeout window
                await asyncio.sleep(0.9)
                assert not w.transport.is_closing()
            finally:
                w.close()
                server.close()
                await server.wait_closed()

        asyncio.run(fn())
        assert stats.read_timeouts == 0

    def test_stalled_body_read_is_closed(self):
        stats, start = self._serve(0.3)

        async def fn():
            server, port = await start()
            try:
                r, w = await asyncio.open_connection("127.0.0.1", port)
                w.write(b"POST /x HTTP/1.1\r\nHost: x\r\n"
                        b"Content-Length: 1000\r\n\r\nonly-a-little")
                await w.drain()
                got = await asyncio.wait_for(r.read(), timeout=3.0)
                assert got == b""
            finally:
                server.close()
                await server.wait_closed()

        asyncio.run(fn())
        assert stats.read_timeouts == 1

    def test_idle_keepalive_connection_untouched(self):
        stats, start = self._serve(0.3)

        async def fn():
            server, port = await start()
            try:
                r, w = await asyncio.open_connection("127.0.0.1", port)
                w.write(b"GET / HTTP/1.1\r\nHost: x\r\n\r\n")  # complete
                await w.drain()
                await asyncio.sleep(0.9)  # idle well past the window
                assert not w.transport.is_closing()
            finally:
                w.close()
                server.close()
                await server.wait_closed()

        asyncio.run(fn())
        assert stats.read_timeouts == 0

    def test_read_timeout_off_is_parity(self):
        # with the flag at 0 the serving path never imports the guard:
        # ServerOptions default keeps read_timeout_s == 0
        assert ServerOptions().read_timeout_s == 0.0

    @pytest.mark.slow
    def test_real_server_closes_slowloris(self, tmp_path):
        """End-to-end: a real `serve()` process with --read-timeout must
        close a stalled header read while a well-behaved request on a
        second connection succeeds."""
        from tests.conftest import free_port

        port = free_port()
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("IMAGINARY_TPU_WORKER", None)
        proc = subprocess.Popen(
            [sys.executable, "-m", "imaginary_tpu.cli", "--port", str(port),
             "--read-timeout", "1.0"],
            cwd=ROOT, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        try:
            end = time.monotonic() + 60
            while time.monotonic() < end:
                try:
                    s = socket.create_connection(("127.0.0.1", port), 1)
                    s.close()
                    break
                except OSError:
                    time.sleep(0.3)
            # slowloris: headers started, never finished
            sl = socket.create_connection(("127.0.0.1", port), 5)
            sl.sendall(b"GET /health HTTP/1.1\r\nHost: x\r\n")
            sl.settimeout(5.0)
            t0 = time.monotonic()
            got = sl.recv(4096)  # server must CLOSE us (b"" = EOF)
            assert got == b"", got
            assert time.monotonic() - t0 < 4.0
            sl.close()
            # a healthy request still answers afterwards
            import urllib.request

            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=5) as r:
                body = json.loads(r.read())
            assert body["worker"] == 0
        finally:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()


# --- supervisor fencing env contract ----------------------------------------


def test_worker_epoch_env_helper():
    from imaginary_tpu.web.workers import WORKER_EPOCH_ENV, worker_epoch

    assert worker_epoch() == 0
    os.environ[WORKER_EPOCH_ENV] = "17"
    try:
        assert worker_epoch() == 17
    finally:
        del os.environ[WORKER_EPOCH_ENV]
