"""End-to-end request deadline tests (imaginary_tpu/deadline.py + the
enforcement hops in web/middleware.py, web/handlers.py, web/sources.py,
pipeline.py).

Covers the ISSUE-4 acceptance surface: budget arithmetic and the
X-Request-Timeout clamp, 504-on-expiry vs 503-shed-at-admission, the
bounded-time guarantee under an injected device delay, and the
cancelled-while-queued path freeing the pool slot (the _inflight ledger
balances through _release_if_cancelled)."""

import asyncio
import json
import time

import pytest

from imaginary_tpu import deadline as deadline_mod
from imaginary_tpu import failpoints
from imaginary_tpu.errors import DeadlineExceeded
from imaginary_tpu.web.config import ServerOptions
from tests.conftest import fixture_bytes
from tests.test_server import run


@pytest.fixture(autouse=True)
def _disarm_failpoints():
    yield
    failpoints.deactivate()


@pytest.fixture(scope="module", autouse=True)
def _fixtures(testdata):
    return testdata


class TestBudgetArithmetic:
    def test_resolve_budget_default(self):
        assert deadline_mod.resolve_budget(5.0, "") == 5.0

    def test_resolve_budget_header_lowers(self):
        assert deadline_mod.resolve_budget(5.0, "2") == 2.0
        assert deadline_mod.resolve_budget(5.0, "0.25") == 0.25

    def test_resolve_budget_header_clamped_to_server_max(self):
        assert deadline_mod.resolve_budget(5.0, "30") == 5.0

    def test_resolve_budget_off_ignores_header(self):
        # a header cannot enable what the operator left off
        assert deadline_mod.resolve_budget(0.0, "2") == 0.0

    def test_resolve_budget_garbage_header_falls_back(self):
        assert deadline_mod.resolve_budget(5.0, "soon") == 5.0
        assert deadline_mod.resolve_budget(5.0, "-1") == 5.0
        assert deadline_mod.resolve_budget(5.0, "0") == 5.0

    def test_deadline_remaining_and_expiry(self):
        d = deadline_mod.Deadline(0.05)
        assert 0.0 < d.remaining_s() <= 0.05
        assert not d.expired()
        time.sleep(0.06)
        assert d.expired()
        assert d.remaining_s() < 0.0

    def test_checkpoints_record_remaining(self):
        d = deadline_mod.Deadline(10.0)
        d.note("fetch")
        d.note("queue")
        stages = d.stages_dict()
        assert set(stages) == {"fetch", "queue"}
        assert all(0 < v <= 10_000 for v in stages.values())

    def test_checkpoints_bounded(self):
        d = deadline_mod.Deadline(10.0)
        for i in range(100):
            d.note(f"s{i}")
        assert len(d.checkpoints) == deadline_mod._MAX_CHECKPOINTS

    def test_check_raises_504_with_breakdown(self):
        d = deadline_mod.Deadline(0.001, t0=time.monotonic() - 1.0)
        with pytest.raises(DeadlineExceeded) as ei:
            d.check("encode")
        err = ei.value
        assert err.http_code() == 504
        body = json.loads(err.json_bytes())
        assert body["status"] == 504
        assert body["stage"] == "encode"
        assert body["elapsed_ms"] >= 1000.0
        assert body["budget_ms"] == 1.0
        assert "deadline exceeded at encode" in body["message"]

    def test_module_check_noop_without_trace(self):
        deadline_mod.check("anything")  # must not raise outside a request

    def test_current_none_without_deadline(self):
        assert deadline_mod.current() is None


class TestDeadlineHTTP:
    """Wire-level semantics through the real app."""

    def test_off_by_default_parity(self):
        """With --request-timeout unset, X-Request-Timeout is inert and
        responses carry no deadline artifacts."""
        async def fn(client, _):
            res = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"),
                headers={"X-Request-Timeout": "0.000001"})
            assert res.status == 200

        run(ServerOptions(), fn)

    def test_generous_budget_serves_normally(self):
        async def fn(client, _):
            res = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"))
            assert res.status == 200

        run(ServerOptions(request_timeout_s=30.0), fn)

    def test_header_lowers_budget_to_504(self):
        """A client-requested 1 ms budget expires mid-flight: 504 with the
        elapsed/budget breakdown, never a hang."""
        failpoints.activate("codec.decode=delay(50ms)")

        async def fn(client, _):
            t0 = time.monotonic()
            res = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"),
                headers={"X-Request-Timeout": "0.001"})
            elapsed = time.monotonic() - t0
            assert res.status == 504
            body = await res.json()
            assert body["budget_ms"] == 1.0
            assert body["elapsed_ms"] >= body["budget_ms"]
            assert "stage" in body
            assert elapsed < 5.0

        run(ServerOptions(request_timeout_s=30.0), fn)

    def test_header_cannot_raise_above_server_max(self):
        """Server max 100 ms + header asking 30 s + a 300 ms device delay:
        the clamp keeps the budget at 100 ms, so the request 504s (an
        unclamped header would have let it succeed)."""
        failpoints.activate("device.execute=delay(300ms)")

        async def fn(client, _):
            t0 = time.monotonic()
            res = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"),
                headers={"X-Request-Timeout": "30"})
            elapsed = time.monotonic() - t0
            assert res.status == 504
            body = await res.json()
            assert body["budget_ms"] == 100.0
            assert elapsed < 3.0

        run(ServerOptions(request_timeout_s=0.1), fn)

    def test_slow_device_504_within_budget_plus_tick(self):
        """The ISSUE-4 acceptance row: 200 ms injected device delay, 150 ms
        budget -> 504 bounded by budget + one scheduler tick, not by the
        device's schedule."""
        failpoints.activate("device.execute=delay(200ms)")

        async def fn(client, _):
            t0 = time.monotonic()
            res = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"))
            elapsed = time.monotonic() - t0
            assert res.status == 504
            # budget 0.15s; generous tick allowance for a loaded CI host,
            # but far below the no-deadline path (decode + 200ms delay +
            # encode) and the old 120 s executor cap
            assert elapsed < 2.0
            body = await res.json()
            assert body["status"] == 504 and "deadline exceeded" in body["message"]

        run(ServerOptions(request_timeout_s=0.15), fn)

    def test_admission_shed_503_when_queue_exceeds_budget(self):
        """Estimated queue delay > remaining budget -> shed 503 with
        Retry-After BEFORE any work (distinct from the 504 after
        admission), even with --max-queue-ms off."""
        async def fn(client, _):
            svc = client.app["service"]
            svc._service_ewma_ms = 10_000.0
            svc._inflight = svc._pool_workers + 50
            res = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"))
            assert res.status == 503
            body = await res.json()
            assert "deadline" in body["message"]
            assert int(res.headers["Retry-After"]) >= 1
            svc._inflight = 0

        run(ServerOptions(request_timeout_s=1.0), fn)

    def test_504_vs_503_vs_shed_triple(self):
        """One app, three outcomes: quiet queue + fat budget -> 200; quiet
        queue + tiny budget + slow decode -> 504; deep queue -> 503."""
        failpoints.activate("codec.decode=delay(80ms)")

        async def fn(client, _):
            svc = client.app["service"]
            ok = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"))
            assert ok.status == 200

            late = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"),
                headers={"X-Request-Timeout": "0.04"})
            assert late.status == 504

            svc._service_ewma_ms = 10_000.0
            svc._inflight = svc._pool_workers + 50
            shed = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"))
            assert shed.status == 503
            svc._inflight = 0

        run(ServerOptions(request_timeout_s=5.0), fn)

    def test_cancelled_while_queued_frees_slot(self):
        """A request whose deadline passes while its pool future is still
        QUEUED is cancelled: the worker never runs it, the 504 lands at
        ~budget (not behind the queue), and _release_if_cancelled balances
        the _inflight ledger back to zero."""
        failpoints.activate("codec.decode=delay(400ms)")

        async def fn(client, _):
            svc = client.app["service"]

            async def occupant():
                # fat budget: rides out the 400 ms decode on the 1 worker
                return await client.post(
                    "/resize?width=100", data=fixture_bytes("imaginary.jpg"))

            async def expiring():
                await asyncio.sleep(0.08)  # arrive while the worker is busy
                t0 = time.monotonic()
                res = await client.post(
                    "/resize?width=100", data=fixture_bytes("imaginary.jpg"),
                    headers={"X-Request-Timeout": "0.1"})
                return res, time.monotonic() - t0

            a, (b, b_elapsed) = await asyncio.gather(occupant(), expiring())
            assert a.status == 200
            assert b.status == 504
            # b resolved at ITS budget, not after the occupant's 400 ms
            assert b_elapsed < 0.35
            # the ledger balanced: nothing leaked from the cancelled task
            for _ in range(50):
                with svc._inflight_lock:
                    if svc._inflight == 0:
                        break
                await asyncio.sleep(0.02)
            with svc._inflight_lock:
                assert svc._inflight == 0

        run(ServerOptions(request_timeout_s=30.0, cpus=1), fn)

    def test_deadline_lands_in_wide_event_surfaces(self):
        """Budget/remaining/per-stage checkpoints ride the slow-ring
        events the /debugz surface serves."""
        from imaginary_tpu.obs.debugz import SLOW

        async def fn(client, _):
            SLOW.clear()
            res = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"))
            assert res.status == 200
            events = SLOW.slowest(256)
            mine = [e for e in events if e.get("deadline_budget_ms") == 7000.0]
            assert mine, "deadline fields missing from the event surface"
            ev = mine[0]
            assert 0.0 < ev["deadline_remaining_ms"] <= 7000.0
            stages = ev["deadline_stages"]
            assert "admission" in stages and "queue" in stages

        run(ServerOptions(request_timeout_s=7.0), fn)

    def test_origin_fetch_bounded_by_deadline(self):
        """A hung origin cannot outlive the request budget: the fetch
        attempt's timeout derives from remaining budget -> 504."""
        from aiohttp import web as aioweb

        async def origin(request):
            await asyncio.sleep(2.0)
            return aioweb.Response(body=b"late")

        async def fn(client, origin_url):
            t0 = time.monotonic()
            res = await client.get(
                f"/resize?width=100&url={origin_url}/img.jpg")
            elapsed = time.monotonic() - t0
            assert res.status == 504
            assert elapsed < 3.0

        run(ServerOptions(enable_url_source=True, request_timeout_s=0.3,
                          source_retries=0), fn, origin_handler=origin)
