"""Device circuit breaker (SURVEY.md section 5.3 analogue): after
breaker_threshold CONSECUTIVE failed device dispatches, host-executable
requests fail over to the host interpreter instead of 400-ing one by one;
a device success closes the breaker."""

import numpy as np
import pytest

from imaginary_tpu.engine import Executor, ExecutorConfig
from imaginary_tpu.engine.executor import last_placement, reset_placement
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.ops.plan import plan_operation


def _img(h=96, w=128, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (h, w, 3), dtype=np.uint8)


def _plan(h=96, w=128, width=48):
    return plan_operation("resize", ImageOptions(width=width), h, w, 0, 3)


@pytest.fixture
def broken_device(monkeypatch):
    """Every device launch raises, as a dead link would."""
    from imaginary_tpu.engine import executor as ex_mod

    def boom(*a, **k):
        raise RuntimeError("link down")

    monkeypatch.setattr(ex_mod.chain_mod, "launch_batch", boom)


def test_breaker_opens_after_consecutive_failures(broken_device):
    ex = Executor(ExecutorConfig(window_ms=1, host_spill=False,
                                 breaker_threshold=3, breaker_cooldown_s=60))
    try:
        # first three device failures surface to their callers...
        for i in range(3):
            with pytest.raises(Exception):
                ex.process(_img(seed=i), _plan(), timeout=30)
        assert ex.stats.device_failures >= 3
        assert ex.stats.breaker_opens == 1
        # ...then the open breaker serves host-executable plans from the
        # host interpreter, no device attempt, correct pixels
        reset_placement()
        out = ex.process(_img(seed=9), _plan())
        assert out.shape == (36, 48, 3)
        assert ex.stats.breaker_host_served == 1
        assert last_placement() == "host"
    finally:
        ex.shutdown()


def test_breaker_serves_yuv_plans_during_outage(broken_device):
    """Packed-transport plans fail over too: the host interpreter returns
    YuvPlanes the raw encoder can consume."""
    from io import BytesIO

    from PIL import Image

    from imaginary_tpu import codecs
    from imaginary_tpu.ops.buckets import bucket_shape
    from imaginary_tpu.ops.plan import wrap_plan_yuv420

    if not codecs.yuv420_supported():
        pytest.skip("native YUV420 codec not built")
    out = BytesIO()
    Image.fromarray(_img(120, 160)).save(out, "JPEG", quality=85, subsampling=2)
    hb, wb = bucket_shape(120, 160)
    packed, h, w, _ = codecs.decode_yuv420(out.getvalue(), 1, hb, wb)
    wrapped = wrap_plan_yuv420(_plan(120, 160, 80), 120, 160)

    ex = Executor(ExecutorConfig(window_ms=1, host_spill=False,
                                 breaker_threshold=2, breaker_cooldown_s=60))
    try:
        for i in range(2):
            with pytest.raises(Exception):
                ex.process(_img(seed=i), _plan(), timeout=30)
        got = ex.process(packed, wrapped)
        assert isinstance(got, codecs.YuvPlanes)
        assert got.y.shape == (60, 80)
        body = codecs.encode_yuv(got, codecs.EncodeOptions())
        assert Image.open(BytesIO(body)).size == (80, 60)
    finally:
        ex.shutdown()


def test_owed_accounting_balances_under_concurrency():
    """The owed-milliseconds ledger (charged at enqueue, released on
    completion) must return to zero after mixed-size concurrent traffic —
    a leak would ratchet the spill policy toward permanent host serving."""
    import threading

    # probes disabled: a shadow's drain may include an XLA compile (minutes
    # on CPU), which would park its charge past any sane polling window —
    # this test is about the ledger of REAL items
    ex = Executor(ExecutorConfig(window_ms=2, host_spill=True,
                                 probe_interval=10**9))
    try:
        # seed the device rate: the FIRST drain of a chain key is
        # compile-cold and excluded from the EWMA, so run each shape twice
        import time

        for s in (100, 101):
            ex.process(_img(seed=s), _plan())
            ex.process(_img(192, 256, seed=s), _plan(192, 256))
        for _ in range(100):
            if ex._device_ms_per_mb is not None:
                break
            time.sleep(0.02)
        assert ex._device_ms_per_mb is not None  # charges are non-zero
        errs = []

        def worker(i):
            try:
                h, w = (96, 128) if i % 3 else (192, 256)
                out = ex.process(_img(h, w, seed=i), _plan(h, w, 48 + (i % 5)))
                assert out.shape[1] == 48 + (i % 5)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        for _ in range(100):  # last futures may still be resolving
            with ex._owed_lock:
                if abs(ex._owed_ms) < 1e-6:
                    break
            time.sleep(0.05)
        with ex._owed_lock:
            assert abs(ex._owed_ms) < 1e-6
    finally:
        ex.shutdown()


def test_breaker_closes_on_device_success(monkeypatch):
    from imaginary_tpu.engine import executor as ex_mod

    real = ex_mod.chain_mod.launch_batch
    fail = {"on": True}

    def flaky(*a, **k):
        if fail["on"]:
            raise RuntimeError("link down")
        return real(*a, **k)

    monkeypatch.setattr(ex_mod.chain_mod, "launch_batch", flaky)
    ex = Executor(ExecutorConfig(window_ms=1, host_spill=False,
                                 breaker_threshold=2, breaker_cooldown_s=0.05))
    try:
        for i in range(2):
            with pytest.raises(Exception):
                ex.process(_img(seed=i), _plan(), timeout=30)
        assert ex.stats.breaker_opens == 1
        fail["on"] = False
        import time

        time.sleep(0.1)  # cooldown expires; next request probes the device
        reset_placement()
        out = ex.process(_img(seed=5), _plan())
        assert out.shape == (36, 48, 3)
        assert last_placement() == "device"
        assert not ex._breaker_is_open()
    finally:
        ex.shutdown()


class TestDrainWatchdog:
    """The breaker's blind spot (measured live on a dying tunnel): a
    half-dead link HANGS inside the runtime instead of erroring, so no
    failure is ever booked and queued requests ride their full client
    timeout. The watchdog abandons the stuck drain, fails its futures
    fast, opens the breaker outright, and hands the queue to a fresh
    fetcher; the zombie drain's results are discarded if the call ever
    returns."""

    def test_hung_drain_abandoned_breaker_opens_and_host_serves(self, monkeypatch):
        import threading

        from imaginary_tpu.engine import executor as ex_mod

        release = threading.Event()
        hung = threading.Event()

        real_fetch = ex_mod.chain_mod.fetch_groups
        calls = {"n": 0}

        def hang_once(groups):
            calls["n"] += 1
            if calls["n"] == 1:
                hung.set()
                release.wait(timeout=30)  # blocked "forever" (test-bounded)
            return real_fetch(groups)

        monkeypatch.setattr(ex_mod.chain_mod, "fetch_groups", hang_once)
        ex = Executor(ExecutorConfig(window_ms=1, host_spill=False,
                                     drain_watchdog_s=0.5,
                                     breaker_cooldown_s=60))
        try:
            fut = ex.submit(_img(), _plan())
            assert hung.wait(timeout=30)  # the drain is now stuck
            with pytest.raises(RuntimeError, match="watchdog"):
                fut.result(timeout=30)  # failed FAST, not at client timeout
            assert ex.stats.breaker_opens == 1
            assert ex.stats.device_failures >= 1
            # host-executable traffic now fails over immediately
            reset_placement()
            out = ex.process(_img(seed=1), _plan(), timeout=30)
            assert out.shape[0] > 0
            assert last_placement() == "host"
            assert ex.stats.breaker_host_served == 1
            # zombie unblocks: its results are discarded without incident,
            # and the replacement fetcher keeps serving once the breaker
            # cooldown is behind us (simulate by closing it)
            release.set()
            with ex._owed_lock:
                ex._breaker_open_until = 0.0
                ex._consec_device_failures = 0
            out2 = ex.process(_img(seed=2), _plan(), timeout=30)
            assert out2.shape[0] > 0
            assert calls["n"] >= 2  # replacement fetcher drained it
        finally:
            release.set()
            ex.shutdown()

    def test_groups_queued_behind_hung_drain_fail_fast(self, monkeypatch):
        import threading

        from imaginary_tpu.engine import executor as ex_mod

        release = threading.Event()
        calls = {"n": 0}

        def hang(groups):
            # only the FIRST drain hangs; any group the collector was
            # still holding when the watchdog drained the queue lands on
            # the REPLACEMENT fetcher, which must fail it fast, not block
            calls["n"] += 1
            if calls["n"] == 1:
                release.wait(timeout=30)
            raise RuntimeError("late failure")

        monkeypatch.setattr(ex_mod.chain_mod, "fetch_groups", hang)
        ex = Executor(ExecutorConfig(window_ms=1, host_spill=False,
                                     drain_watchdog_s=0.5,
                                     breaker_cooldown_s=60))
        try:
            futs = [ex.submit(_img(seed=i), _plan()) for i in range(3)]
            for f in futs:
                with pytest.raises(RuntimeError):
                    f.result(timeout=30)
        finally:
            release.set()
            ex.shutdown()
