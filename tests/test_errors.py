"""Error machinery tests (modeled on error_test.go:5-24)."""

import json

import pytest

from imaginary_tpu.errors import ErrNotFound, ImageError, new_error


def test_error_shape():
    e = new_error("oops", 400)
    assert e.message == "oops"
    assert e.http_code() == 400
    body = json.loads(e.json_bytes())
    assert body == {"message": "oops", "status": 400}


def test_error_strips_newlines():
    e = new_error("multi\nline\nmessage", 400)
    assert e.message == "multilinemessage"


def test_http_code_clamped():
    assert new_error("x", 200).http_code() == 503
    assert new_error("x", 399).http_code() == 503
    assert new_error("x", 512).http_code() == 503
    assert new_error("x", 400).http_code() == 400
    assert new_error("x", 511).http_code() == 511


def test_predefined():
    assert ErrNotFound.code == 404
    assert isinstance(ErrNotFound, ImageError)


class TestRequiredParamMessages:
    """The per-op required-param guards, graded against the reference's
    EXACT wire messages (image.go:115-310) — clients match on these."""

    CASES = [
        ("resize", {}, "Missing required param: height or width"),
        ("enlarge", {"width": 400}, "Missing required params: height, width"),
        ("extract", {"top": 10}, "Missing required params: areawidth or areaheight"),
        ("crop", {}, "Missing required param: height or width"),
        ("smartcrop", {}, "Missing required param: height or width"),
        ("rotate", {}, "Missing required param: rotate"),
        ("zoom", {}, "Missing required param: factor"),
        ("zoom", {"factor": 2, "top": 10},
         "Missing required params: areawidth, areaheight"),
        ("convert", {}, "Missing required param: type"),
        ("blur", {}, "Missing required param: sigma or minampl"),
    ]

    @pytest.mark.parametrize("op,kw,msg", CASES,
                             ids=[f"{c[0]}-{i}" for i, c in enumerate(CASES)])
    def test_exact_message(self, op, kw, msg):
        from imaginary_tpu.options import ImageOptions
        from imaginary_tpu.pipeline import process_operation
        from tests.conftest import fixture_bytes

        o = ImageOptions(**kw)
        for k in kw:
            o.mark_defined(k)
        with pytest.raises(ImageError) as ei:
            process_operation(op, fixture_bytes("imaginary.jpg"), o)
        assert ei.value.message == msg
        assert ei.value.http_code() == 400
