"""Error machinery tests (modeled on error_test.go:5-24)."""

import json

from imaginary_tpu.errors import ErrNotFound, ImageError, new_error


def test_error_shape():
    e = new_error("oops", 400)
    assert e.message == "oops"
    assert e.http_code() == 400
    body = json.loads(e.json_bytes())
    assert body == {"message": "oops", "status": 400}


def test_error_strips_newlines():
    e = new_error("multi\nline\nmessage", 400)
    assert e.message == "multilinemessage"


def test_http_code_clamped():
    assert new_error("x", 200).http_code() == 503
    assert new_error("x", 399).http_code() == 503
    assert new_error("x", 512).http_code() == 503
    assert new_error("x", 400).http_code() == 400
    assert new_error("x", 511).http_code() == 511


def test_predefined():
    assert ErrNotFound.code == 404
    assert isinstance(ErrNotFound, ImageError)
