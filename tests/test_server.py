"""HTTP integration tests (modeled on server_test.go).

Each test spins an in-process aiohttp app (and, where needed, a fake origin
server — the reference's httptest.NewServer pattern, server_test.go:282-285)
and asserts on the wire: status, headers, and decoded output dimensions via
PIL.
"""

import asyncio
import io
import json

import numpy as np
import pytest
from aiohttp import FormData
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

from imaginary_tpu.web.app import create_app
from imaginary_tpu.web.config import ServerOptions, parse_origins
from imaginary_tpu.web.middleware import sign_url
from tests.conftest import FIXTURES, fixture_bytes


def run(options, fn, origin_handler=None):
    """Run `fn(client, origin_url)` against a fresh app instance."""

    async def runner():
        from aiohttp import web

        origin_url = None
        origin = None
        if origin_handler is not None:
            oapp = web.Application()
            oapp.router.add_route("*", "/{tail:.*}", origin_handler)
            origin = TestServer(oapp)
            await origin.start_server()
            origin_url = f"http://127.0.0.1:{origin.port}"

        app = create_app(options, log_stream=io.StringIO())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await fn(client, origin_url)
        finally:
            await client.close()
            if origin is not None:
                await origin.close()

    asyncio.run(runner())


def oracle_size(body: bytes):
    im = Image.open(io.BytesIO(body))
    return im.width, im.height


def multipart_jpg():
    form = FormData()
    form.add_field("file", fixture_bytes("imaginary.jpg"),
                   filename="imaginary.jpg", content_type="image/jpeg")
    return form


@pytest.fixture(scope="module", autouse=True)
def _fixtures(testdata):
    return testdata


class TestPublicEndpoints:
    def test_index_versions(self):
        async def fn(client, _):
            res = await client.get("/")
            assert res.status == 200
            body = await res.json()
            assert "imaginary_tpu" in body and "jax" in body
            assert res.headers["Server"].startswith("imaginary-tpu")

        run(ServerOptions(), fn)

    def test_health(self):
        async def fn(client, _):
            res = await client.get("/health")
            body = await res.json()
            assert res.status == 200
            assert body["uptime"] >= 0 and "executor" in body

        run(ServerOptions(), fn)

    def test_form_html(self):
        async def fn(client, _):
            res = await client.get("/form")
            text = await res.text()
            assert res.status == 200
            assert 'action="/resize' in text and "multipart/form-data" in text

        run(ServerOptions(), fn)

    def test_unknown_path_404(self):
        async def fn(client, _):
            res = await client.get("/bogus-path")
            assert res.status == 404

        run(ServerOptions(), fn)

    def test_method_not_allowed(self):
        async def fn(client, _):
            res = await client.delete("/resize")
            assert res.status == 405

        run(ServerOptions(), fn)


class TestImagePost:
    def test_crop_multipart(self):
        async def fn(client, _):
            res = await client.post("/crop?width=300", data=multipart_jpg())
            assert res.status == 200, await res.text()
            assert res.headers["Content-Type"] == "image/jpeg"
            body = await res.read()
            assert oracle_size(body) == (300, 740)

        run(ServerOptions(), fn)

    def test_resize_raw_body(self):
        async def fn(client, _):
            res = await client.post(
                "/resize?width=200&height=150",
                data=fixture_bytes("imaginary.jpg"),
                headers={"Content-Type": "image/jpeg"},
            )
            assert res.status == 200
            assert oracle_size(await res.read()) == (200, 150)

        run(ServerOptions(), fn)

    def test_empty_body_400(self):
        async def fn(client, _):
            res = await client.post("/resize?width=200", data=b"",
                                    headers={"Content-Type": "image/jpeg"})
            assert res.status == 400

        run(ServerOptions(), fn)

    def test_non_image_payload_406(self):
        async def fn(client, _):
            res = await client.post("/resize?width=200", data=b"clearly not an image",
                                    headers={"Content-Type": "image/jpeg"})
            assert res.status == 406

        run(ServerOptions(), fn)

    def test_bad_param_400(self):
        async def fn(client, _):
            res = await client.post("/resize?width=bogus", data=multipart_jpg())
            assert res.status == 400
            body = await res.json()
            assert "width" in body["message"]

        run(ServerOptions(), fn)

    def test_info(self):
        async def fn(client, _):
            res = await client.post("/info", data=multipart_jpg())
            meta = await res.json()
            assert meta["width"] == 550 and meta["height"] == 740

        run(ServerOptions(), fn)

    def test_pipeline(self):
        async def fn(client, _):
            ops = json.dumps([
                {"operation": "crop", "params": {"width": 300, "height": 260}},
                {"operation": "convert", "params": {"type": "webp"}},
            ])
            res = await client.post(f"/pipeline?operations={ops}", data=multipart_jpg())
            assert res.status == 200, await res.text()
            assert res.headers["Content-Type"] == "image/webp"
            assert oracle_size(await res.read()) == (300, 260)

        run(ServerOptions(), fn)


class TestTypeAuto:
    """ref: TestTypeAuto server_test.go:178-233."""

    def test_accept_webp(self):
        async def fn(client, _):
            res = await client.post("/resize?width=100&type=auto", data=multipart_jpg(),
                                    headers={"Accept": "image/webp,*/*"})
            assert res.status == 200
            assert res.headers["Content-Type"] == "image/webp"
            assert res.headers["Vary"] == "Accept"

        run(ServerOptions(), fn)

    def test_chrome_accept_header(self):
        chrome = "text/html,application/xhtml+xml,application/xml;q=0.9,image/avif,image/webp,image/apng,*/*;q=0.8"
        async def fn(client, _):
            res = await client.post("/resize?width=100&type=auto", data=multipart_jpg(),
                                    headers={"Accept": chrome})
            assert res.headers["Content-Type"] == "image/webp"
            assert res.headers["Vary"] == "Accept"

        run(ServerOptions(), fn)

    def test_no_accept_keeps_source(self):
        async def fn(client, _):
            res = await client.post("/resize?width=100&type=auto", data=multipart_jpg())
            assert res.headers["Content-Type"] == "image/jpeg"
            assert res.headers["Vary"] == "Accept"

        run(ServerOptions(), fn)

    def test_invalid_type_400(self):
        async def fn(client, _):
            res = await client.post("/resize?width=100&type=bogus", data=multipart_jpg())
            assert res.status == 400

        run(ServerOptions(), fn)


class TestResolutionGuard:
    def test_too_many_pixels_422(self):
        async def fn(client, _):
            res = await client.post("/resize?width=100", data=multipart_jpg())
            assert res.status == 422

        run(ServerOptions(max_allowed_pixels=0.1), fn)


class TestMountSource:
    def test_fs_serving(self):
        async def fn(client, _):
            res = await client.get("/resize?file=imaginary.jpg&width=300")
            assert res.status == 200
            assert oracle_size(await res.read()) == (300, 404)

        run(ServerOptions(mount=FIXTURES), fn)

    def test_path_traversal_rejected(self):
        async def fn(client, _):
            res = await client.get("/resize?file=../../etc/passwd&width=100")
            assert res.status == 400

        run(ServerOptions(mount=FIXTURES), fn)

    def test_missing_file_400(self):
        async def fn(client, _):
            res = await client.get("/resize?file=nope.jpg&width=100")
            assert res.status == 400

        run(ServerOptions(mount=FIXTURES), fn)

    def test_get_without_sources_405(self):
        async def fn(client, _):
            res = await client.get("/resize?width=100")
            assert res.status == 405

        run(ServerOptions(), fn)


class TestURLSource:
    def test_remote_fetch(self):
        from aiohttp import web

        async def origin(request):
            return web.Response(body=fixture_bytes("large.jpg"), content_type="image/jpeg")

        async def fn(client, origin_url):
            res = await client.get(f"/resize?url={origin_url}/img.jpg&width=300")
            assert res.status == 200
            w, h = oracle_size(await res.read())
            assert w == 300

        run(ServerOptions(enable_url_source=True), fn, origin_handler=origin)

    def test_origin_error_maps_to_502(self):
        """An origin error is OUR gateway failure, not the client's fault:
        the origin's status stays in the message only (PARITY.md r8 — the
        reference re-raised it verbatim, leaking e.g. an origin 401 as an
        imaginary-tpu auth failure)."""
        from aiohttp import web

        async def origin(request):
            return web.Response(status=404, text="not here")

        async def fn(client, origin_url):
            res = await client.get(f"/resize?url={origin_url}/gone.jpg&width=300")
            assert res.status == 502
            body = await res.json()
            assert "status=404" in body["message"]

        run(ServerOptions(enable_url_source=True), fn, origin_handler=origin)

    def test_restricted_origin(self):
        from aiohttp import web

        async def origin(request):
            return web.Response(body=fixture_bytes("large.jpg"), content_type="image/jpeg")

        async def fn(client, origin_url):
            res = await client.get(f"/resize?url={origin_url}/img.jpg&width=300")
            assert res.status == 400
            body = await res.json()
            assert "not allowed" in body["message"]

        run(
            ServerOptions(enable_url_source=True,
                          allowed_origins=parse_origins("https://images.example.com")),
            fn,
            origin_handler=origin,
        )

    def test_invalid_url_400(self):
        async def fn(client, _):
            res = await client.get("/resize?url=not-a-url&width=300")
            assert res.status == 400

        run(ServerOptions(enable_url_source=True), fn)


class TestAuthAndSignature:
    def test_api_key(self):
        async def fn(client, _):
            res = await client.post("/crop?width=100", data=multipart_jpg())
            assert res.status == 401
            res = await client.post("/crop?width=100", data=multipart_jpg(),
                                    headers={"API-Key": "s3cret"})
            assert res.status == 200
            res = await client.post("/crop?width=100&key=s3cret", data=multipart_jpg())
            assert res.status == 200

        run(ServerOptions(api_key="s3cret"), fn)

    def test_url_signature(self):
        key = "x" * 32

        async def fn(client, _):
            pairs = [("width", "100")]
            sig = sign_url(key, "/crop", pairs)
            res = await client.post(f"/crop?width=100&sign={sig}", data=multipart_jpg())
            assert res.status == 200
            res = await client.post("/crop?width=100&sign=invalid!!", data=multipart_jpg())
            assert res.status == 400
            bad = sign_url(key, "/crop", [("width", "999")])
            res = await client.post(f"/crop?width=100&sign={bad}", data=multipart_jpg())
            assert res.status == 403

        run(ServerOptions(enable_url_signature=True, url_signature_key=key), fn)


class TestMiddlewareExtras:
    def test_throttle_429(self):
        async def fn(client, _):
            first = await client.post("/crop?width=50", data=multipart_jpg())
            assert first.status == 200
            second = await client.post("/crop?width=50", data=multipart_jpg())
            assert second.status == 429
            assert "Retry-After" in second.headers

        run(ServerOptions(concurrency=1, burst=0), fn)

    def test_disabled_endpoint_501(self):
        async def fn(client, _):
            res = await client.post("/blur?sigma=3", data=multipart_jpg())
            assert res.status == 501
            res = await client.post("/crop?width=50", data=multipart_jpg())
            assert res.status == 200

        run(ServerOptions(endpoints=("blur",)), fn)

    def test_cache_headers(self):
        async def fn(client, _):
            res = await client.get("/resize?file=imaginary.jpg&width=100")
            assert res.headers["Cache-Control"] == "public, s-maxage=300, max-age=300, no-transform"
            assert "Expires" in res.headers
            # public paths excluded
            res = await client.get("/health")
            assert "Cache-Control" not in res.headers

        run(ServerOptions(mount=FIXTURES, http_cache_ttl=300), fn)

    def test_no_cache_ttl_zero(self):
        async def fn(client, _):
            res = await client.get("/resize?file=imaginary.jpg&width=100")
            assert res.headers["Cache-Control"] == "private, no-cache, no-store, must-revalidate"

        run(ServerOptions(mount=FIXTURES, http_cache_ttl=0), fn)

    def test_cors_headers(self):
        async def fn(client, _):
            res = await client.post("/crop?width=50", data=multipart_jpg())
            assert res.headers["Access-Control-Allow-Origin"] == "*"

        run(ServerOptions(cors=True), fn)

    def test_return_size_headers(self):
        async def fn(client, _):
            res = await client.post("/crop?width=120&height=90", data=multipart_jpg())
            assert res.headers["Image-Width"] == "120"
            assert res.headers["Image-Height"] == "90"

        run(ServerOptions(return_size=True), fn)


class TestPlaceholder:
    def test_placeholder_on_error(self):
        async def fn(client, _):
            # GET with no source configured would 405; use a failing decode
            res = await client.post("/resize?width=120&height=90", data=b"not an image",
                                    headers={"Content-Type": "image/jpeg"})
            assert res.status == 406  # original error status preserved
            assert res.headers["Content-Type"] == "image/jpeg"
            assert "Error" in res.headers
            assert oracle_size(await res.read()) == (120, 90)

        run(ServerOptions(enable_placeholder=True), fn)

    def test_placeholder_custom_status(self):
        async def fn(client, _):
            res = await client.post("/resize?width=60&height=60", data=b"junk",
                                    headers={"Content-Type": "image/jpeg"})
            assert res.status == 202

        run(ServerOptions(enable_placeholder=True, placeholder_status=202), fn)


class TestPathPrefix:
    def test_prefixed_routes(self):
        async def fn(client, _):
            res = await client.post("/api/v1/crop?width=50", data=multipart_jpg())
            assert res.status == 200
            res = await client.get("/api/v1/health")
            assert res.status == 200

        run(ServerOptions(path_prefix="/api/v1"), fn)


class TestBackendHeader:
    """X-Imaginary-Backend: operators must be able to detect mixed-backend
    traffic (spilled pixels are PSNR-equivalent, not bit-identical)."""

    def test_device_placement_header(self):
        async def fn(client, _):
            res = await client.post("/resize?width=100", data=multipart_jpg())
            assert res.status == 200
            assert res.headers["X-Imaginary-Backend"] == "device"
            # identity plans (re-encode only) never reach the executor but
            # still carry the header: untouched pixels cannot diverge
            res = await client.post("/convert?type=png", data=multipart_jpg())
            assert res.status == 200
            assert res.headers["X-Imaginary-Backend"] == "device"
            # /info never produces pixels: no header
            res = await client.post("/info", data=multipart_jpg())
            assert res.status == 200
            assert "X-Imaginary-Backend" not in res.headers

        run(ServerOptions(), fn)

    def test_host_spill_cli_flag(self):
        from imaginary_tpu.cli import build_parser, options_from_args

        for val, expect in (("auto", None), ("on", True), ("off", False)):
            args = build_parser().parse_args(["--host-spill", val])
            assert options_from_args(args).host_spill is expect
        # default is auto
        args = build_parser().parse_args([])
        assert options_from_args(args).host_spill is None


class TestGCRAEviction:
    def test_key_cap_evicts(self):
        """The TAT map is bounded like the reference's memstore
        (middleware.go:131, NewMemStore(65536)): rekeying the limiter by
        client must not leak memory."""
        import time as _time

        from imaginary_tpu.web.middleware import GCRARateLimiter

        rl = GCRARateLimiter(per_sec=1000, burst=1)
        rl.MAX_KEYS = 8  # shadow the class cap for the test
        for i in range(50):
            rl.allow(f"client-{i}")
        assert len(rl._tat) <= 8
        # expired entries are preferred victims: after their tat passes,
        # new keys slot in without nuking live state wholesale
        _time.sleep(0.005)
        rl.allow("fresh")
        assert "fresh" in rl._tat and len(rl._tat) <= 8

    def test_flood_does_not_reset_throttled_clients(self):
        """A unique-key flood must not wipe a throttled client's state
        (that would be a rate-limit bypass): eviction keeps the
        LARGEST-tat half, and a client throttled through its burst
        allowance has accumulated tat far above a one-shot flood key's."""
        from imaginary_tpu.web.middleware import GCRARateLimiter

        rl = GCRARateLimiter(per_sec=10, burst=3)  # emission 0.1s, tau 0.3s
        rl.MAX_KEYS = 8
        for _ in range(4):  # burn the burst: tat climbs ~0.4s ahead
            rl.allow("victim")
        blocked, retry = rl.allow("victim")
        assert not blocked and retry > 0  # throttled now
        for i in range(20):  # live-key flood past the cap
            rl.allow(f"flood-{i}")
        assert "victim" in rl._tat, "flood evicted a throttled client"
        still_blocked, _ = rl.allow("victim")
        assert not still_blocked, "flood reset a throttled client's TAT"


class TestSpatialServedRequest:
    """The W-axis spatial sharding engages on a SERVED request over the
    (batch x spatial) mesh (VERDICT r3 next #7 asked for a served-path
    proof, not just the executor-level test): request through HTTP, output
    dims exact, /health's executor counters show a spatial batch."""

    def test_served_request_routes_spatially(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        import numpy as np

        o = ServerOptions(
            use_mesh=True,
            spatial=2,
            # tiny threshold so the test doesn't pay a 4K-bucket XLA
            # compile on CPU; the sharding machinery is identical
            spatial_threshold_px=1,
            host_spill=False,
        )
        rng = np.random.default_rng(8)
        png = io.BytesIO()
        Image.fromarray(rng.integers(0, 256, (256, 512, 3), dtype=np.uint8)).save(
            png, "PNG"
        )
        form = FormData()
        form.add_field("file", png.getvalue(), filename="t.png",
                       content_type="image/png")

        async def fn(client, _origin):
            r = await client.post("/resize?width=128&type=png", data=form)
            assert r.status == 200
            body = await r.read()
            assert oracle_size(body) == (128, 64)
            h = await client.get("/health")
            stats = (await h.json())["executor"]
            assert stats["spatial_batches"] >= 1

        run(o, fn)


class TestTLSConfig:
    """TLS context mirrors the reference's pinned config (server.go:114-131):
    TLS >= 1.2, the ECDHE + AES-GCM/ChaCha20 cipher list, and — on
    Python >= 3.13, where ssl grew set_groups — the X25519/P-256/P-384
    curve preference list; older interpreters keep OpenSSL's default
    order (X25519-first anyway) rather than pinning wrong via the
    single-curve set_ecdh_curve."""

    def test_ssl_context_pins_reference_ciphers(self, tmp_path):
        import ssl
        import subprocess

        crt, key = tmp_path / "t.crt", tmp_path / "t.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(crt), "-days", "1",
             "-subj", "/CN=localhost"],
            check=True, capture_output=True,
        )
        from imaginary_tpu.web.app import make_ssl_context

        o = ServerOptions(cert_file=str(crt), key_file=str(key))
        ctx = make_ssl_context(o)
        assert ctx is not None
        assert ctx.minimum_version == ssl.TLSVersion.TLSv1_2
        names = {c["name"] for c in ctx.get_ciphers()}
        # every pinned TLS1.2 suite is ECDHE with AEAD; no CBC/RSA-kex leaks
        tls12 = {n for n in names if not n.startswith("TLS_")}
        assert tls12 == {
            "ECDHE-ECDSA-AES256-GCM-SHA384", "ECDHE-RSA-AES256-GCM-SHA384",
            "ECDHE-ECDSA-AES128-GCM-SHA256", "ECDHE-RSA-AES128-GCM-SHA256",
            "ECDHE-ECDSA-CHACHA20-POLY1305", "ECDHE-RSA-CHACHA20-POLY1305",
        }

    def test_no_tls_without_both_files(self):
        from imaginary_tpu.web.app import make_ssl_context

        assert make_ssl_context(ServerOptions(cert_file="/tmp/x.crt")) is None

    def test_group_pinning_on_py313(self):
        """On >= 3.13 the context pins the reference's curve list via
        set_groups; this interpreter may be older, so the helper is
        proven against stand-ins on both sides of the version gate."""
        import ssl as ssl_mod
        import sys

        from imaginary_tpu.web.app import _pin_groups

        calls = []

        class WithGroups:  # the >= 3.13 surface
            def set_groups(self, groups):
                calls.append(groups)

        class WithoutGroups:  # pre-3.13 surface
            pass

        assert _pin_groups(WithGroups()) is True
        assert calls == ["x25519:prime256v1:secp384r1"]
        assert _pin_groups(WithoutGroups()) is False
        # and the real context takes whichever branch this interpreter has
        ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_SERVER)
        assert _pin_groups(ctx) is (sys.version_info >= (3, 13))


class TestMultipartFieldOverride:
    """?field= selects the multipart form field name — documented by the
    reference (README.md:597, default `file`) though its fork hard-codes
    `file` (source_body.go:12); this build follows the docs."""

    def test_custom_field_name_accepted(self):
        async def fn(client, _):
            form = FormData()
            form.add_field("photo", fixture_bytes("imaginary.jpg"),
                           filename="p.jpg", content_type="image/jpeg")
            r = await client.post("/resize?width=100&field=photo", data=form)
            assert r.status == 200
            assert oracle_size(await r.read())[0] == 100

        run(ServerOptions(), fn)

    def test_default_field_still_file(self):
        async def fn(client, _):
            r = await client.post("/resize?width=100", data=multipart_jpg())
            assert r.status == 200

        run(ServerOptions(), fn)

    def test_wrong_field_is_missing_file_error(self):
        async def fn(client, _):
            form = FormData()
            form.add_field("photo", fixture_bytes("imaginary.jpg"),
                           filename="p.jpg", content_type="image/jpeg")
            # no ?field= -> the `photo` part is invisible, like the ref
            r = await client.post("/resize?width=100", data=form)
            assert r.status == 400

        run(ServerOptions(), fn)


class TestBootLivenessGate:
    """A dead/hung accelerator tunnel blocks INSIDE the runtime at first
    use; the CLI probes liveness in a subprocess before serving and
    either falls back to CPU loudly or dies cleanly (--require-device)."""

    def test_require_device_refuses_to_start(self, monkeypatch):
        from imaginary_tpu import cli

        # the gate only runs when no platform pin is present (a pinned
        # platform is an explicit operator decision); the test env pins
        # cpu, so clear it
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        monkeypatch.delenv("IMAGINARY_TPU_PLATFORM", raising=False)
        monkeypatch.setattr(cli, "_start_device_probe",
                            lambda **kw: object())
        monkeypatch.setattr(cli, "_finish_device_probe",
                            lambda p, timeout=75.0: (False, "link down"))
        assert cli.main(["--require-device", "--port", "0"]) == 2

    def test_default_falls_back_to_cpu(self, monkeypatch):
        import jax

        from imaginary_tpu import cli
        from imaginary_tpu.web import app as app_mod

        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        monkeypatch.delenv("IMAGINARY_TPU_PLATFORM", raising=False)
        monkeypatch.setattr(cli, "_start_device_probe",
                            lambda **kw: object())
        monkeypatch.setattr(cli, "_finish_device_probe",
                            lambda p, timeout=75.0: (False, "link down"))

        served = {}

        async def fake_serve(o, mrelease=30):
            served["platform"] = jax.config.jax_platforms

        monkeypatch.setattr(app_mod, "serve", fake_serve)
        before = jax.config.jax_platforms
        try:
            assert cli.main(["--port", "0"]) == 0
            assert served["platform"] == "cpu"  # loud CPU fallback engaged
        finally:
            jax.config.update("jax_platforms", before or "cpu")

    def test_probe_times_out_cleanly(self):
        from imaginary_tpu import cli

        # 50 ms is far below any real jax import: the subprocess probe
        # must time out and report dead with a diagnostic, not hang
        alive, diag = cli._finish_device_probe(cli._start_device_probe(),
                                               timeout=0.05)
        assert alive is False
        assert "hung" in diag

    def test_require_device_probes_even_with_platform_pin(self, monkeypatch):
        """A pinned platform is an operator choice of BACKEND, not proof
        of liveness: --require-device must still verify it."""
        from imaginary_tpu import cli

        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        monkeypatch.setattr(cli, "_start_device_probe",
                            lambda **kw: object())
        monkeypatch.setattr(cli, "_finish_device_probe",
                            lambda p, timeout=75.0: (False, "pinned but dead"))
        assert cli.main(["--require-device", "--port", "0"]) == 2

    def test_require_device_rejects_clean_cpu_fallback(self):
        """jax silently degrades to the CPU backend when the accelerator
        plugin is absent or fails without hanging; with --require-device
        the probe must treat that as DEAD, not alive (a liveness-only
        probe would exit 0 and boot the server on CPU). On this CPU-only
        host the child's non-CPU assert fires, proving the refusal."""
        from imaginary_tpu import cli

        alive, diag = cli._finish_device_probe(
            cli._start_device_probe(platform="cpu", require_accel=True))
        assert alive is False
        assert "CPU backend" in diag

    def test_probe_forwards_platform_pin_to_child(self, monkeypatch):
        """The probe must run the SAME backend the server will: the pin
        is re-applied via jax.config inside the child (env JAX_PLATFORMS
        is NOT enough — the tunnel plugin overrides it at boot)."""
        from imaginary_tpu import cli

        captured = {}
        import subprocess as sp

        real_popen = sp.Popen

        def spy(cmd, **kw):
            captured["code"] = cmd[-1]
            return real_popen([cmd[0], "-c", "pass"], stdout=sp.DEVNULL,
                              stderr=sp.PIPE)

        monkeypatch.setattr(sp, "Popen", spy)
        proc = cli._start_device_probe(platform="cpu", require_accel=False)
        cli._finish_device_probe(proc)
        assert "jax.config.update('jax_platforms', 'cpu')" in captured["code"]
        assert "assert" not in captured["code"]  # accel check only when asked


class TestQueueDepthAdmission:
    """--max-queue-ms sheds load with a 503 when the estimated queueing
    delay (host backlog + executor owed-work ledger) exceeds the bound —
    GCRA caps the RATE, this caps the DEPTH an overload can pile up
    (r4 weak: closed-loop p99 reached 450+ ms unbounded)."""

    def test_overloaded_queue_sheds_with_503(self):
        async def fn(client, _):
            svc = client.app["service"]
            svc._service_ewma_ms = 10_000.0  # simulate a saturated pool...
            svc._inflight = svc._pool_workers + 50  # ...with deep backlog
            resp = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"))
            assert resp.status == 503
            body = await resp.json()
            assert body["message"] == "Server queue is full, retry later"
            # the shed carries a backoff hint like the rate-limit 503 (r8)
            assert int(resp.headers["Retry-After"]) >= 1

        run(ServerOptions(max_queue_ms=200.0), fn)

    def test_quiet_queue_admits(self):
        async def fn(client, _):
            resp = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"))
            assert resp.status == 200

        run(ServerOptions(max_queue_ms=200.0), fn)

    def test_disabled_by_default(self):
        async def fn(client, _):
            svc = client.app["service"]
            svc._service_ewma_ms = 10_000.0
            svc._inflight = svc._pool_workers + 50
            resp = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"))
            assert resp.status == 200  # 0 = no depth gate (r4 behavior)
            svc._inflight = 0

        run(ServerOptions(), fn)

    def test_shutdown_drain_sheds_with_retry_after(self):
        """During the shutdown grace window new image work 503s fast with
        a Retry-After (another instance takes the retry); /health stays
        live so the balancer can see the drain."""
        async def fn(client, _):
            client.app["draining"] = True
            resp = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"))
            assert resp.status == 503
            assert resp.headers["Retry-After"] == "2"
            health = await client.get("/health")
            assert health.status == 200

        run(ServerOptions(), fn)

    def test_estimate_combines_host_and_device(self):
        async def fn(client, _):
            svc = client.app["service"]
            base = svc.estimated_queue_ms()
            svc._inflight = svc._pool_workers + svc._pool_workers  # backlog = workers
            bumped = svc.estimated_queue_ms()
            assert bumped >= base + svc._service_ewma_ms * 0.9
            svc._inflight = 0

        run(ServerOptions(), fn)

    def test_gate_recovers_when_queue_drains(self):
        """Regression: the estimate must exclude the link's fixed drain
        floor — on a slow backend (CPU-fallback floor ~670 ms) counting
        it latched the gate shut FOREVER after one burst (an idle server
        reading as permanently backlogged)."""
        async def fn(client, _):
            svc = client.app["service"]
            # a slow link's fixed floor, far above the bound
            svc.executor._drain_floor_ms = 700.0
            assert svc.executor.estimated_wait_ms() == 0.0  # floor excluded
            resp = await client.post(
                "/resize?width=100", data=fixture_bytes("imaginary.jpg"))
            assert resp.status == 200  # idle server admits despite floor

        run(ServerOptions(max_queue_ms=150.0), fn)



class TestInflightLedgerOnCancellation:
    """Regression (the --max-queue-ms latch-shut leak): a request
    cancelled while its pool task is still QUEUED never runs
    _process_sync (whose finally normally decrements _inflight). The
    submit + done-callback path must balance the ledger for exactly the
    cancelled-while-queued outcome — and only that one."""

    def test_cancelled_queued_request_releases_inflight(self):
        import threading

        from aiohttp.test_utils import make_mocked_request

        async def fn(client, _):
            svc = client.app["service"]
            release = threading.Event()
            started = threading.Event()

            def blocker():
                started.set()
                release.wait(15)

            # saturate every pool worker so the next request sits queued
            blockers = [svc.pool.submit(blocker)
                        for _ in range(svc._pool_workers)]
            assert started.wait(5)
            base = svc._inflight
            # drive the real handler coroutine and cancel it the way a
            # disconnect-cancelled request would be (aiohttp's default
            # config doesn't cancel handlers, but middleware timeouts and
            # handler_cancellation deployments do — the ledger must
            # survive either way)
            req = make_mocked_request("POST", "/resize?width=100")
            task = asyncio.ensure_future(
                svc._process_and_respond(req, "resize",
                                         fixture_bytes("imaginary.jpg")))
            # wait for the handler to increment the ledger and enqueue its
            # pool task (it can never START: all workers are blocked)
            for _ in range(500):
                if svc._inflight > base:
                    break
                await asyncio.sleep(0.01)
            assert svc._inflight == base + 1
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
            # the done-callback fires when the cancelled pool task is
            # discarded; give it a beat
            for _ in range(500):
                if svc._inflight == base:
                    break
                await asyncio.sleep(0.01)
            assert svc._inflight == base, "cancelled-while-queued leaked"
            release.set()
            for b in blockers:
                b.result(timeout=10)

        run(ServerOptions(cpus=1), fn)

    def test_completed_request_never_double_decrements(self):
        async def fn(client, _):
            svc = client.app["service"]
            for _ in range(3):
                resp = await client.post(
                    "/resize?width=100", data=fixture_bytes("imaginary.jpg"))
                assert resp.status == 200
            # ran-to-completion futures are not cancelled(), so only
            # _process_sync's finally decrements: the ledger sits at zero,
            # not negative
            assert svc._inflight == 0

        run(ServerOptions(cpus=2), fn)


class TestMetricsEndpoint:
    """Prometheus /metrics (above-reference: SURVEY 5.5 notes the
    reference has no Prometheus surface). Same numbers as /health in
    exposition format; public like /health."""

    def test_metrics_shape(self):
        async def fn(client, _):
            # process one image so executor counters are live
            await client.post("/resize?width=100", data=multipart_jpg())
            res = await client.get("/metrics")
            assert res.status == 200
            assert res.headers["Content-Type"].startswith("text/plain")
            text = await res.text()
            lines = dict(
                ln.rsplit(" ", 1) for ln in text.strip().splitlines()
                if " " in ln and not ln.startswith("#")
            )
            assert float(lines["imaginary_tpu_uptime"]) >= 0
            assert "imaginary_tpu_pid" in lines
            assert float(lines["imaginary_tpu_executor_items"]) >= 0
            assert float(lines["imaginary_tpu_estimated_queue_ms"]) >= 0
            assert any(k.startswith('imaginary_tpu_backend_info{backend=')
                       for k in lines)
            # per-stage latency gauges carry stage/quantile labels
            assert any(k.startswith('imaginary_tpu_stage_ms{stage="')
                       for k in lines)

        run(ServerOptions(), fn)

    def test_metrics_gated_like_health(self):
        """Exactly /health's auth posture: the reference wires ALL routes
        through the API-key middleware (server.go:73-76), so a scraper
        needs the key when one is set."""
        async def fn(client, _):
            res = await client.get("/metrics")
            assert res.status == 401
            res = await client.get("/metrics", headers={"API-Key": "sekrit"})
            assert res.status == 200

        run(ServerOptions(api_key="sekrit"), fn)


class TestShouldRestrictOriginMatrix:
    """The reference's full allowed-origins matrix, ported verbatim
    (source_http_test.go:300-443): wildcard subdomains, path prefixes,
    double slashes, trailing-slash normalization, bucket pairs, and the
    trailing-* path wildcard (parseOrigins strips it to a raw prefix,
    imaginary.go:314-321 — r5 fix: our parse previously kept both the
    `*` and the missing-slash laxness, so `/assets` wrongly admitted
    `/assetsevil/..`)."""

    def _restricted(self, url, origins_csv):
        from urllib.parse import urlparse as up

        from imaginary_tpu.web.sources import should_restrict_origin

        return should_restrict_origin(up(url), parse_origins(origins_csv))

    PLAIN = "https://example.org"
    WILD = ("https://localhost,https://*.example.org,"
            "https://some.s3.bucket.on.aws.org,https://*.s3.bucket.on.aws.org")
    WITH_PATH = ("https://localhost/foo/bar/,https://*.example.org/foo/,"
                 "https://some.s3.bucket.on.aws.org/my/bucket/,"
                 "https://*.s3.bucket.on.aws.org/my/bucket/,"
                 "https://no-leading-path-slash.example.org/assets")
    TWO_BUCKETS = ("https://some.s3.bucket.on.aws.org/my/bucket1/,"
                   "https://some.s3.bucket.on.aws.org/my/bucket2/")
    PATH_WILDCARD = "https://some.s3.bucket.on.aws.org/my-bucket-name*"

    @pytest.mark.parametrize("url,origins,allowed", [
        # plain origin
        ("https://example.org/logo.jpg", PLAIN, True),
        # wildcard origin, plain / sub / sub-sub domain URLs
        ("https://example.org/logo.jpg", WILD, True),
        ("https://node-42.example.org/logo.jpg", WILD, True),
        ("https://n.s3.bucket.on.aws.org/our/bucket/logo.jpg", WILD, True),
        # incorrect domain: restricted under both configs
        ("https://myexample.org/logo.jpg", PLAIN, False),
        ("https://myexample.org/logo.jpg", WILD, False),
        # loopback origin with path
        ("https://localhost/foo/bar/logo.png", WITH_PATH, True),
        ("https://localhost/wrong/logo.png", WITH_PATH, False),
        # wildcard origin with (partial) path
        ("https://our.company.s3.bucket.on.aws.org/my/bucket/logo.gif",
         WITH_PATH, True),
        ("https://our.company.s3.bucket.on.aws.org/my/bucket/a/b/c/d/e/logo.gif",
         WITH_PATH, True),
        # double slashes inside the URL path
        ("https://static.example.org/foo//a//b//c/d/e/logo.webp",
         WITH_PATH, True),
        # origin path missing its trailing slash still matches its subtree
        ("https://no-leading-path-slash.example.org/assets/logo.webp",
         "https://*.example.org/assets", True),
        # ...but must NOT leak prefix-sibling paths (normalization adds /)
        ("https://no-leading-path-slash.example.org/assetsevil/logo.webp",
         "https://*.example.org/assets", False),
        # two buckets on one host
        ("https://some.s3.bucket.on.aws.org/my/bucket1/logo.jpg", TWO_BUCKETS, True),
        ("https://some.s3.bucket.on.aws.org/my/bucket2/logo.jpg", TWO_BUCKETS, True),
        # trailing-* path wildcard: raw prefix
        ("https://some.s3.bucket.on.aws.org/my-bucket-name/logo.jpg",
         PATH_WILDCARD, True),
        ("https://some.s3.bucket.on.aws.org/my-other-bucket-name/logo.jpg",
         PATH_WILDCARD, False),
    ])
    def test_matrix(self, url, origins, allowed):
        assert self._restricted(url, origins) is (not allowed)


class TestAccessLogContract:
    """log_test.go ported: info level logs a 200 line carrying method,
    HTTP version and status; error level emits NOTHING for a 200
    (log.go:88-99). Plus the level gates the reference implies but never
    tests: warning catches 4xx, error catches 5xx."""

    def _capture(self, level, fn_inner):
        stream = io.StringIO()

        async def runner():
            app = create_app(ServerOptions(log_level=level), log_stream=stream)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                await fn_inner(client)
            finally:
                await client.close()

        asyncio.run(runner())
        return stream.getvalue()

    def test_info_logs_full_line(self):
        async def fn(client):
            await client.get("/health")

        line = self._capture("info", fn)
        assert "GET" in line and "HTTP/1.1" in line and " 200 " in line
        # Apache-ish shape with 4-decimal latency (log.go:12,31), a
        # timezone-offset timestamp, and the trailing request id
        import re

        assert re.search(r'" 200 \d+ \d+\.\d{4} [0-9a-f]{32}\n', line)
        assert re.search(r'\[\d{2}/\w{3}/\d{4}:\d{2}:\d{2}:\d{2} [+-]\d{4}\]', line)

    def test_error_level_silent_on_200(self):
        async def fn(client):
            await client.get("/health")

        assert self._capture("error", fn) == ""

    def test_warning_catches_4xx_not_2xx(self):
        async def fn(client):
            await client.get("/health")          # 200: silent
            await client.get("/bogus-route")     # 404: logged

        line = self._capture("warning", fn)
        assert " 200 " not in line and " 404 " in line


class TestMaxAllowedSize:
    """source_http_test.go:270-298 ported: a remote image larger than
    -max-allowed-size must be refused via the HEAD Content-Length
    pre-check (source_http.go:83-87,105-124), exercised with the
    1024-byte fixture against a 1023-byte cap."""

    def test_oversized_remote_rejected(self):
        from aiohttp import web

        blob = fixture_bytes("1024bytes")

        async def origin(request):
            return web.Response(body=blob,
                                content_type="application/octet-stream")

        async def fn(client, origin_url):
            res = await client.get(f"/resize?url={origin_url}/img.jpg&width=100")
            # 413 to match the GET-side streaming cap (r8; was 400)
            assert res.status == 413
            body = await res.json()
            assert "exceeds maximum allowed" in body["message"]

        run(ServerOptions(enable_url_source=True, max_allowed_size=1023),
            fn, origin_handler=origin)

    def test_within_cap_fetches(self):
        from aiohttp import web

        blob = fixture_bytes("imaginary.jpg")

        async def origin(request):
            return web.Response(body=blob, content_type="image/jpeg")

        async def fn(client, origin_url):
            res = await client.get(f"/resize?url={origin_url}/img.jpg&width=100")
            assert res.status == 200

        run(ServerOptions(enable_url_source=True,
                          max_allowed_size=len(blob) + 100),
            fn, origin_handler=origin)

    def test_head_failure_degrades_to_capped_get(self):
        """The HEAD pre-check is advisory (r8): an origin that errors the
        HEAD (many CDNs 403 it) degrades to the size-capped GET instead of
        failing a request the GET path can serve."""
        from aiohttp import web

        async def origin(request):
            if request.method == "HEAD":
                return web.Response(status=403)
            return web.Response(body=fixture_bytes("imaginary.jpg"),
                                content_type="image/jpeg")

        async def fn(client, origin_url):
            res = await client.get(f"/resize?url={origin_url}/img.jpg&width=100")
            assert res.status == 200

        run(ServerOptions(enable_url_source=True, max_allowed_size=10_000_000),
            fn, origin_handler=origin)

    def test_head_oversize_still_capped_by_get(self):
        """A lying/failed HEAD cannot bypass the size budget: the GET-side
        streaming cap still rejects an oversize body with 413."""
        from aiohttp import web

        blob = fixture_bytes("1024bytes")

        async def origin(request):
            if request.method == "HEAD":
                return web.Response(status=500)
            return web.Response(body=blob,
                                content_type="application/octet-stream")

        async def fn(client, origin_url):
            res = await client.get(f"/resize?url={origin_url}/img.jpg&width=100")
            assert res.status == 413

        run(ServerOptions(enable_url_source=True, max_allowed_size=1023),
            fn, origin_handler=origin)
