"""Multi-tenant QoS tests (ISSUE 5).

Four layers, mirroring the qos package:

  * tenancy: --qos-config parsing/validation + per-request resolution
  * limiter: per-tenant GCRA overrides AND the shared store's key-flood
    eviction branch (the MAX_KEYS sweep/evict path the tentpole rekeys
    by tenant — previously untested)
  * sched:   the fair-scheduler invariants — FIFO parity with qos off,
    strict priority, bounded-aging no-starvation, EDF within a class,
    per-tenant share caps with the 503 + Retry-After contract
  * HTTP:    the wired surfaces — 429 JSON/placeholder bodies, RED
    counting, class-graded shedding, qos.admit failpoint, /health,
    /metrics (strict exposition), /debugz, wide-event stamping, and
    qos-off byte parity
"""

import asyncio
import io
import json
import queue as queue_mod

import pytest
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

from imaginary_tpu import failpoints
from imaginary_tpu.qos import CLASSES
from imaginary_tpu.qos.limiter import TenantLimiter
from imaginary_tpu.qos.sched import FairScheduler
from imaginary_tpu.qos.shed import TenantShareExceeded
from imaginary_tpu.qos.tenancy import (
    TenantSpec,
    load_policy,
    parse_policy,
    request_qos,
)
from imaginary_tpu.web.config import ServerOptions
from imaginary_tpu.web.middleware import GCRARateLimiter


def policy(**overrides):
    """A small two-tenant policy: gold=interactive (keyed), hog=batch
    (ip-matched, 1/16 queue share on a 64-slot queue -> cap 4)."""
    doc = {
        "default": {"class": "standard"},
        "tenants": [
            {"name": "gold", "class": "interactive",
             "api_keys": ["gold-key"]},
            {"name": "hog", "class": "batch", "ips": ["10.9.9.9"],
             "max_share": 1.0 / 16.0},
        ],
        "queue_cap": 64,
    }
    doc.update(overrides)
    return parse_policy(json.dumps(doc))


class Item:
    """Stand-in for the executor's _Item: the scheduler only reads .qos."""

    def __init__(self, qos=None, tag=None):
        self.qos = qos
        self.tag = tag


def drain(sched, n):
    return [sched.get_nowait().tag for _ in range(n)]


# --- tenancy ------------------------------------------------------------------


class TestPolicyParsing:
    def test_empty_is_off(self):
        assert load_policy("") is None
        assert load_policy("   ") is None

    def test_file_path(self, tmp_path):
        p = tmp_path / "qos.json"
        p.write_text(json.dumps({"default": {"class": "batch"}}))
        pol = load_policy(str(p))
        assert pol.default.klass == "batch"

    def test_missing_file_fails_loudly(self):
        with pytest.raises(ValueError, match="cannot read"):
            load_policy("/nonexistent/qos.json")

    def test_unknown_class_rejected(self):
        with pytest.raises(ValueError, match="unknown class"):
            parse_policy('{"default": {"class": "platinum"}}')

    def test_unknown_keys_rejected(self):
        with pytest.raises(ValueError, match="unknown top-level"):
            parse_policy('{"tenantz": []}')
        with pytest.raises(ValueError, match="unknown key"):
            parse_policy('{"default": {"clazz": "batch"}}')

    def test_bad_max_share_rejected(self):
        with pytest.raises(ValueError, match="max_share"):
            parse_policy('{"default": {"max_share": 0}}')
        with pytest.raises(ValueError, match="max_share"):
            parse_policy('{"default": {"max_share": 1.5}}')

    def test_duplicate_tenant_rejected(self):
        doc = {"tenants": [
            {"name": "a", "api_keys": ["x"]},
            {"name": "a", "api_keys": ["y"]},
        ]}
        with pytest.raises(ValueError, match="duplicate"):
            parse_policy(json.dumps(doc))

    def test_unmatchable_tenant_rejected(self):
        with pytest.raises(ValueError, match="matches nothing"):
            parse_policy('{"tenants": [{"name": "ghost"}]}')

    def test_default_cannot_carry_keys(self):
        with pytest.raises(ValueError, match="default tenant cannot"):
            parse_policy('{"default": {"api_keys": ["k"]}}')

    def test_invalid_json(self):
        with pytest.raises(ValueError, match="invalid JSON"):
            parse_policy("{nope")

    def test_snapshot_never_leaks_keys(self):
        snap = policy().snapshot()
        assert "gold-key" not in json.dumps(snap)
        gold = next(t for t in snap["tenants"] if t["name"] == "gold")
        assert gold["api_keys"] == 1  # a count, not the credential

    def test_request_qos_defaults_outside_request(self):
        name, kidx, share, deadline_t = request_qos(policy())
        assert name == "default" and CLASSES[kidx] == "standard"
        assert share == 1.0 and deadline_t is None


# --- limiter (satellite: the GCRA key-flood eviction branch) ------------------


class TestGCRAEviction:
    def test_expired_entry_sweep(self, monkeypatch):
        """When the store hits MAX_KEYS, expired entries (tat in the
        past) are dropped FIRST; live entries keep their state."""
        import time as time_mod

        monkeypatch.setattr(GCRARateLimiter, "MAX_KEYS", 8)
        lim = GCRARateLimiter(per_sec=1, burst=0)
        now = time_mod.monotonic()
        # 7 expired keys + 1 live (throttled: tat far in the future)
        for i in range(7):
            lim._tat[f"old{i}"] = now - 10.0
        lim._tat["live"] = now + 100.0
        allowed, _ = lim.allow("newcomer")
        assert allowed
        assert "newcomer" in lim._tat
        # the sweep dropped only the expired keys; the throttled client
        # kept its state and is still throttled
        assert all(f"old{i}" not in lim._tat for i in range(7))
        blocked, retry = lim.allow("live")
        assert not blocked and retry > 0

    def test_oldest_tat_half_eviction_keeps_throttled(self, monkeypatch):
        """All-live flood: the oldest-tat half evicts; clients closest to
        throttle (largest tat) keep their state."""
        import time as time_mod

        monkeypatch.setattr(GCRARateLimiter, "MAX_KEYS", 8)
        lim = GCRARateLimiter(per_sec=1, burst=0)
        now = time_mod.monotonic()
        for i in range(8):
            lim._tat[f"k{i}"] = now + 10.0 + i  # all live, k7 most throttled
        lim.allow("flood")
        # kept: the MAX_KEYS//2 largest tats (k4..k7)
        assert all(f"k{i}" in lim._tat for i in range(4, 8))
        assert all(f"k{i}" not in lim._tat for i in range(4))
        blocked, _ = lim.allow("k7")
        assert not blocked

    def test_throttle_state_survives_flood(self, monkeypatch):
        """End-to-end: throttle a client, flood with fresh keys past
        MAX_KEYS, the throttled client is STILL throttled."""
        monkeypatch.setattr(GCRARateLimiter, "MAX_KEYS", 16)
        lim = GCRARateLimiter(per_sec=1, burst=1)
        for _ in range(5):
            lim.allow("victim")  # drive tat well past now
        assert lim.allow("victim")[0] is False
        for i in range(40):
            lim.allow(f"flood{i}")
        assert lim.allow("victim")[0] is False

    def test_per_key_override_params(self):
        """The qos layer's per-tenant emission/tau ride per call over one
        shared store: a strict tenant throttles while a generous one
        flows, in the same limiter."""
        lim = GCRARateLimiter(per_sec=1000, burst=100)
        strict = dict(emission=1.0, tau=0.0)  # 1 rps, no burst
        assert lim.allow("t:strict", **strict)[0] is True
        assert lim.allow("t:strict", **strict)[0] is False
        for _ in range(20):
            assert lim.allow("t:generous")[0] is True  # global params


class TestTenantLimiter:
    def test_tenant_rate_overrides_global(self):
        tl = TenantLimiter(global_rate=1000, global_burst=100)
        strict = TenantSpec(name="s", rate=1.0, burst=0)
        assert tl.allow(strict)[0] is True
        allowed, retry = tl.allow(strict)
        assert allowed is False and retry > 0

    def test_inherits_global_when_no_rate(self):
        tl = TenantLimiter(global_rate=1, global_burst=0)
        ten = TenantSpec(name="t")
        assert tl.allow(ten)[0] is True
        assert tl.allow(ten)[0] is False

    def test_unlimited_mints_no_state(self):
        tl = TenantLimiter(global_rate=0, global_burst=0)
        ten = TenantSpec(name="anon")
        for _ in range(100):
            assert tl.allow(ten) == (True, 0.0)
        assert len(tl._gcra._tat) == 0  # no key churn for unlimited tenants

    def test_tenants_do_not_share_buckets(self):
        tl = TenantLimiter(global_rate=1, global_burst=0)
        assert tl.allow(TenantSpec(name="a"))[0] is True
        assert tl.allow(TenantSpec(name="b"))[0] is True  # own key
        assert tl.allow(TenantSpec(name="a"))[0] is False


# --- sched --------------------------------------------------------------------


class TestFairScheduler:
    def test_fifo_parity_default_tenant(self):
        """qos on with nothing but the default tenant orders EXACTLY like
        the seed FIFO queue (no deadlines -> (inf, seq) heap keys)."""
        s = FairScheduler(policy())
        for i in range(32):
            s.put(Item(tag=i))
        assert drain(s, 32) == list(range(32))

    def test_sentinel_never_overtakes_items(self):
        s = FairScheduler(policy())
        s.put(Item(tag="a"))
        s.put(None)  # shutdown sentinel
        assert s.get_nowait().tag == "a"
        assert s.get_nowait() is None
        assert s.get(timeout=0.01) is None  # closed stays closed

    def test_get_timeout_raises_empty(self):
        s = FairScheduler(policy())
        with pytest.raises(queue_mod.Empty):
            s.get(timeout=0.01)
        with pytest.raises(queue_mod.Empty):
            s.get_nowait()

    def test_strict_priority_between_classes(self):
        s = FairScheduler(policy())
        s.put(Item(qos=("hog", 2, 1.0, None), tag="b"))
        s.put(Item(qos=("default", 1, 1.0, None), tag="s"))
        s.put(Item(qos=("gold", 0, 1.0, None), tag="i"))
        assert drain(s, 3) == ["i", "s", "b"]

    def test_aging_bounds_batch_starvation(self):
        """Under a sustained interactive flood, a waiting batch item
        STILL dispatches within aging_dispatches[batch] pops (the
        no-starvation invariant pure strict priority lacks)."""
        pol = policy()
        aging = pol.aging_dispatches[2]
        s = FairScheduler(pol)
        s.put(Item(qos=("hog", 2, 1.0, None), tag="batch"))
        # keep the interactive heap non-empty the whole time
        for i in range(aging + 4):
            s.put(Item(qos=("gold", 0, 1.0, None), tag=f"i{i}"))
        order = []
        for _ in range(aging + 1):
            got = s.get_nowait().tag
            order.append(got)
            s.put(Item(qos=("gold", 0, 1.0, None), tag="refill"))
        assert "batch" in order, f"batch starved through {order}"
        assert order.index("batch") <= aging

    def test_aging_respects_configured_threshold(self):
        pol = policy(aging_dispatches={"batch": 3})
        s = FairScheduler(pol)
        s.put(Item(qos=("hog", 2, 1.0, None), tag="batch"))
        for i in range(8):
            s.put(Item(qos=("gold", 0, 1.0, None), tag=f"i{i}"))
        order = drain(s, 4)
        assert order == ["i0", "i1", "i2", "batch"]

    def test_edf_within_class(self):
        """PR-4 deadlines order a class earliest-expiry-first; items
        without a deadline sort last, in arrival order."""
        s = FairScheduler(policy())
        s.put(Item(qos=("d", 1, 1.0, None), tag="none1"))
        s.put(Item(qos=("d", 1, 1.0, 200.0), tag="late"))
        s.put(Item(qos=("d", 1, 1.0, 50.0), tag="early"))
        s.put(Item(qos=("d", 1, 1.0, None), tag="none2"))
        assert drain(s, 4) == ["early", "late", "none1", "none2"]

    def test_edf_does_not_cross_classes(self):
        """A desperate batch deadline still yields to interactive (class
        boundaries are strict; EDF orders only WITHIN a class)."""
        s = FairScheduler(policy())
        s.put(Item(qos=("hog", 2, 1.0, 1.0), tag="b-urgent"))
        s.put(Item(qos=("gold", 0, 1.0, 9999.0), tag="i-relaxed"))
        assert drain(s, 2) == ["i-relaxed", "b-urgent"]

    def test_tenant_share_cap_rejects_n_plus_1(self):
        """hog's max_share is 1/16 of a 64-slot queue -> cap 4: the 5th
        queued item raises the 503 + Retry-After shed contract, and a pop
        frees a slot."""
        s = FairScheduler(policy())
        hog = ("hog", 2, 1.0 / 16.0, None)
        for i in range(4):
            s.put(Item(qos=hog, tag=i))
        with pytest.raises(TenantShareExceeded) as exc:
            s.put(Item(qos=hog, tag=4))
        assert exc.value.http_code() == 503
        assert exc.value.headers.get("Retry-After") == "1"
        assert "hog" in exc.value.message
        s.get_nowait()
        s.put(Item(qos=hog, tag="fits-again"))  # slot freed

    def test_share_cap_does_not_limit_other_tenants(self):
        s = FairScheduler(policy())
        for i in range(4):
            s.put(Item(qos=("hog", 2, 1.0 / 16.0, None)))
        for i in range(40):  # full-share tenant is uncapped
            s.put(Item(qos=("gold", 0, 1.0, None)))
        assert s.qsize() == 44

    def test_depths_and_stats(self):
        pol = policy()
        s = FairScheduler(pol)
        s.put(Item(qos=("gold", 0, 1.0, None)))
        s.put(Item(qos=("hog", 2, 1.0, None)))
        assert s.depths() == {"interactive": 1, "standard": 0, "batch": 1}
        stats = pol.stats.to_dict()["classes"]
        assert stats["interactive"]["queued"] == 1
        assert stats["batch"]["queued"] == 1
        s.get_nowait()
        assert pol.stats.to_dict()["classes"]["interactive"]["dispatched"] == 1

    def test_blocking_get_wakes_on_put(self):
        import threading

        s = FairScheduler(policy())
        got = []
        t = threading.Thread(target=lambda: got.append(s.get(timeout=5.0)))
        t.start()
        s.put(Item(tag="wake"))
        t.join(timeout=5.0)
        assert not t.is_alive() and got[0].tag == "wake"


class TestExecutorIntegration:
    def test_fifo_queue_without_qos(self):
        from imaginary_tpu.engine.executor import Executor

        ex = Executor()
        try:
            assert isinstance(ex._queue, queue_mod.Queue)
            assert "qos_queued" not in ex.debug_snapshot()
        finally:
            ex.shutdown()

    def test_fair_scheduler_with_qos(self):
        from imaginary_tpu.engine.executor import Executor, ExecutorConfig

        ex = Executor(ExecutorConfig(qos=policy()))
        try:
            assert isinstance(ex._queue, FairScheduler)
            snap = ex.debug_snapshot()
            assert snap["qos_queued"] == {c: 0 for c in CLASSES}
        finally:
            ex.shutdown()

    def test_share_cap_refunds_owed_ledger(self):
        """A submit rejected by the share cap must cancel the future and
        release its owed-ms charge (the charge/refund pair around the
        scheduler put in Executor.submit): the overload estimate must not
        count work that was never queued."""
        import numpy as np

        from imaginary_tpu.engine.executor import Executor, ExecutorConfig
        from imaginary_tpu.options import ImageOptions
        from imaginary_tpu.ops.plan import plan_operation

        ex = Executor(ExecutorConfig(qos=policy(), host_spill=False))
        try:
            ex._device_ms_per_mb = 5.0  # price the link so the charge is real

            def reject(_item):
                raise TenantShareExceeded("hog")

            ex._queue.put = reject  # instance override; deleted below
            arr = np.zeros((64, 64, 3), dtype=np.uint8)
            plan = plan_operation("resize", ImageOptions(width=32),
                                  64, 64, 0, 3)
            with pytest.raises(TenantShareExceeded):
                ex.submit(arr, plan)
            assert ex.estimated_wait_ms() == 0.0
        finally:
            del ex._queue.put  # restore for the shutdown sentinel
            ex.shutdown()


# --- HTTP surfaces ------------------------------------------------------------


def small_jpeg():
    im = Image.new("RGB", (64, 48), (120, 30, 200))
    b = io.BytesIO()
    im.save(b, "JPEG", quality=90)
    return b.getvalue()


def multipart():
    from aiohttp import FormData

    form = FormData()
    form.add_field("file", small_jpeg(), filename="t.jpg",
                   content_type="image/jpeg")
    return form


def run(options, fn):
    """Run `fn(client, app)` against a fresh in-process app."""

    async def runner():
        from imaginary_tpu.web.app import create_app

        app = create_app(options, log_stream=io.StringIO())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await fn(client, app)
        finally:
            await client.close()

    asyncio.run(runner())


QOS_CFG = json.dumps({
    "default": {"class": "standard"},
    "tenants": [
        {"name": "gold", "class": "interactive", "api_keys": ["gold-key"]},
        {"name": "bulk", "class": "batch", "api_keys": ["bulk-key"]},
        {"name": "lim", "class": "standard", "api_keys": ["lim-key"],
         "rate": 1, "burst": 0},
    ],
})


class TestThrottle429:
    """Satellite: the 429 carries the JSON ImageError body (placeholder
    honored) and lands in the RED counters like every terminal status."""

    def test_429_json_body_without_qos(self):
        async def fn(client, app):
            # burst=1: 3rd immediate request exceeds tau
            statuses = []
            for _ in range(4):
                r = await client.get("/health")
                statuses.append(r.status)
                last = r
            assert 429 in statuses
            assert last.status == 429
            assert last.headers["Retry-After"].isdigit()
            body = await last.json()
            assert body == {"message": "Too Many Requests", "status": 429}
            assert last.content_type == "application/json"

        run(ServerOptions(concurrency=1, burst=1), fn)

    def test_429_placeholder_body(self):
        async def fn(client, app):
            last = None
            for _ in range(4):
                last = await client.get("/resize?width=50&height=40")
            assert last.status == 429
            assert last.content_type.startswith("image/")
            err = json.loads(last.headers["Error"])
            assert err["status"] == 429
            im = Image.open(io.BytesIO(await last.read()))
            assert (im.width, im.height) == (50, 40)

        run(ServerOptions(concurrency=1, burst=1, enable_placeholder=True,
                          mount="/tmp"), fn)

    def test_429_counted_in_red_counters(self):
        async def fn(client, app):
            # per-tenant limit: lim is 1 rps/no burst; default unlimited
            assert (await client.get(
                "/health", headers={"API-Key": "lim-key"})).status == 200
            r = await client.get("/health", headers={"API-Key": "lim-key"})
            assert r.status == 429
            text = await (await client.get("/metrics")).text()
            from tests.test_obs import parse_exposition_strict

            _, samples = parse_exposition_strict(text)
            red = {(dict(labels).get("route"), dict(labels).get("code")): v
                   for n, labels, v in samples
                   if n == "imaginary_tpu_requests_total"}
            assert red.get(("/health", "4xx"), 0) >= 1

        run(ServerOptions(qos_config=QOS_CFG), fn)


class TestTenantHTTP:
    def test_per_tenant_limit_leaves_others_alone(self):
        async def fn(client, app):
            assert (await client.get(
                "/health", headers={"API-Key": "lim-key"})).status == 200
            assert (await client.get(
                "/health", headers={"API-Key": "lim-key"})).status == 429
            # gold and anonymous traffic are unlimited (global rate 0)
            for _ in range(5):
                assert (await client.get(
                    "/health", headers={"API-Key": "gold-key"})).status == 200
                assert (await client.get("/health")).status == 200

        run(ServerOptions(qos_config=QOS_CFG), fn)

    def test_rate_limited_counter_by_class(self):
        async def fn(client, app):
            await client.get("/health", headers={"API-Key": "lim-key"})
            await client.get("/health", headers={"API-Key": "lim-key"})
            stats = app["service"].qos.stats.to_dict()["classes"]
            assert stats["standard"]["rate_limited"] >= 1

        run(ServerOptions(qos_config=QOS_CFG), fn)

    def test_tenant_stamped_on_trace_surfaces(self):
        async def fn(client, app):
            from imaginary_tpu.obs.debugz import SLOW

            SLOW.clear()  # the ring is process-global; drop other tests' events
            r = await client.post("/resize?width=32", data=multipart(),
                                  headers={"API-Key": "gold-key"})
            assert r.status == 200
            rid = r.headers["X-Request-ID"]
            d = await (await client.get("/debugz")).json()
            ev = next(e for e in d["slowest_requests"]
                      if e["request_id"] == rid)
            assert ev["tenant"] == "gold"
            assert ev["qos_class"] == "interactive"
            assert d["qos"]["queue_cap"] == 256
            assert d["executor"]["qos_queued"] == {c: 0 for c in CLASSES}

        run(ServerOptions(qos_config=QOS_CFG, enable_debug=True), fn)

    def test_wide_event_carries_tenant(self):
        stream = io.StringIO()

        async def fn(client, app):
            r = await client.post("/resize?width=32", data=multipart(),
                                  headers={"API-Key": "bulk-key"})
            assert r.status == 200

        async def runner():
            from imaginary_tpu.web.app import create_app

            app = create_app(
                ServerOptions(qos_config=QOS_CFG, wide_events=True),
                log_stream=stream)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                await fn(client, app)
            finally:
                await client.close()

        asyncio.run(runner())
        events = [json.loads(line) for line in stream.getvalue().splitlines()
                  if line.startswith("{")]
        ev = next(e for e in events if e.get("op") == "resize")
        assert ev["tenant"] == "bulk" and ev["qos_class"] == "batch"


class TestClassShedding:
    def test_lowest_class_sheds_first(self):
        """With estimated queue delay between the batch and interactive
        thresholds, batch is refused 503 while interactive still serves
        (DAGOR shed order)."""

        async def fn(client, app):
            svc = app["service"]
            svc.estimated_queue_ms = lambda: 60.0  # 50 < 60 < 75 < 100
            r = await client.post("/resize?width=32", data=multipart(),
                                  headers={"API-Key": "bulk-key"})
            assert r.status == 503
            assert r.headers["Retry-After"].isdigit()
            assert (await r.json())["status"] == 503
            r = await client.post("/resize?width=32", data=multipart(),
                                  headers={"API-Key": "gold-key"})
            assert r.status == 200
            stats = svc.qos.stats.to_dict()["classes"]
            assert stats["batch"]["shed"] == 1
            assert stats["interactive"]["admitted"] == 1

        run(ServerOptions(qos_config=QOS_CFG, max_queue_ms=100.0), fn)

    def test_standard_sheds_between(self):
        async def fn(client, app):
            app["service"].estimated_queue_ms = lambda: 80.0  # > 75
            r = await client.post("/resize?width=32", data=multipart())
            assert r.status == 503

        run(ServerOptions(qos_config=QOS_CFG, max_queue_ms=100.0), fn)

    def test_without_qos_single_threshold(self):
        async def fn(client, app):
            app["service"].estimated_queue_ms = lambda: 60.0
            r = await client.post("/resize?width=32", data=multipart())
            assert r.status == 200  # 60 < 100: no class grading, no shed

        run(ServerOptions(max_queue_ms=100.0), fn)


class TestAdmitFailpoint:
    def test_injected_shed_decision(self):
        async def fn(client, app):
            failpoints.activate("qos.admit=error")
            try:
                r = await client.post("/resize?width=32", data=multipart(),
                                      headers={"API-Key": "bulk-key"})
                assert r.status == 503
                assert r.headers["Retry-After"] == "1"
                body = await r.json()
                assert "shed" in body["message"]
            finally:
                failpoints.deactivate()
            # disarmed: same request serves
            r = await client.post("/resize?width=32", data=multipart())
            assert r.status == 200
            stats = app["service"].qos.stats.to_dict()["classes"]
            assert stats["batch"]["shed"] == 1

        run(ServerOptions(qos_config=QOS_CFG), fn)

    def test_once_wrapper_sheds_exactly_one(self):
        async def fn(client, app):
            failpoints.activate("qos.admit=once(error)")
            try:
                first = await client.post("/resize?width=32",
                                          data=multipart())
                second = await client.post("/resize?width=32",
                                           data=multipart())
                assert first.status == 503 and second.status == 200
            finally:
                failpoints.deactivate()

        run(ServerOptions(), fn)  # the site fires with qos off too


class TestQosSurfaces:
    def test_health_and_metrics_blocks(self):
        async def fn(client, app):
            r = await client.post("/resize?width=32", data=multipart(),
                                  headers={"API-Key": "gold-key"})
            assert r.status == 200
            h = await (await client.get("/health")).json()
            assert set(h["qos"]["classes"]) == set(CLASSES)
            assert h["qos"]["classes"]["interactive"]["admitted"] >= 1
            text = await (await client.get("/metrics")).text()
            from tests.test_obs import parse_exposition_strict

            types, samples = parse_exposition_strict(text)
            assert types["imaginary_tpu_qos_queued"] == "gauge"
            assert types["imaginary_tpu_qos_shed_total"] == "counter"
            qos_names = {n for n, _, _ in samples if "qos" in n}
            assert {"imaginary_tpu_qos_queued",
                    "imaginary_tpu_qos_admitted_total",
                    "imaginary_tpu_qos_shed_total",
                    "imaginary_tpu_qos_share_rejected_total",
                    "imaginary_tpu_qos_rate_limited_total",
                    "imaginary_tpu_qos_dispatched_total"} <= qos_names
            admitted = [v for n, labels, v in samples
                        if n == "imaginary_tpu_qos_admitted_total"
                        and dict(labels)["class"] == "interactive"]
            assert admitted and admitted[0] >= 1

        run(ServerOptions(qos_config=QOS_CFG), fn)

    def test_qos_off_surfaces_absent(self):
        async def fn(client, app):
            h = await (await client.get("/health")).json()
            assert "qos" not in h
            text = await (await client.get("/metrics")).text()
            assert "imaginary_tpu_qos_" not in text

        run(ServerOptions(), fn)


class TestQosOffParity:
    def test_qos_off_and_default_config_byte_identical(self):
        """The acceptance pin: qos OFF and qos ON with a pure-default
        config produce byte-identical image responses."""
        bodies = {}

        def capture(tag, options):
            async def fn(client, app):
                r = await client.post("/resize?width=48&height=36",
                                      data=multipart())
                assert r.status == 200
                bodies[tag] = await r.read()

            run(options, fn)

        capture("off", ServerOptions())
        capture("on", ServerOptions(qos_config='{"default": {}}'))
        assert bodies["off"] == bodies["on"]

    def test_cli_flag_roundtrip(self):
        from imaginary_tpu.cli import build_parser, options_from_args

        args = build_parser().parse_args(["--qos-config", '{"default": {}}'])
        o = options_from_args(args)
        assert o.qos_config == '{"default": {}}'
        with pytest.raises(SystemExit):
            options_from_args(build_parser().parse_args(
                ["--qos-config", '{"default": {"class": "bogus"}}']))
