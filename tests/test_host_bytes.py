"""Zero-copy host path: byte-touch ledger parity across cache tiers,
streaming-ingress 413-before-read, codec arena reuse/eviction, and the
dct shrink-on-load spill parity (ISSUE 17 acceptance surface).
"""

import asyncio
import io

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

from imaginary_tpu.engine.timing import COPIES
from imaginary_tpu.errors import ImageError
from imaginary_tpu.web.app import create_app
from imaginary_tpu.web.config import ServerOptions
from tests.conftest import fixture_bytes


@pytest.fixture(scope="module", autouse=True)
def _fixtures(testdata):
    return testdata


def _serve(options, fn):
    async def runner():
        app = create_app(options, log_stream=io.StringIO())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await fn(client)
        finally:
            await client.close()

    asyncio.run(runner())


async def _resize(client, buf):
    COPIES.reset()
    res = await client.post("/resize?width=120&height=80", data=buf,
                            headers={"Content-Type": "image/jpeg"})
    body = await res.read()
    assert res.status == 200, await res.text()
    return COPIES.snapshot(), body


class TestCacheHitLedgerParity:
    """A cache hit on EITHER tier books exactly one cache_hit copy (the
    single read of the stored body) and nothing else beyond the ingress
    read — local LRU and fleet shm grade on the same bar."""

    def _hit_snapshot(self, options):
        buf = fixture_bytes("imaginary.jpg")
        out = {}

        async def fn(client):
            miss_snap, miss_body = await _resize(client, buf)
            hit_snap, hit_body = await _resize(client, buf)
            assert hit_body == miss_body
            out["miss"] = miss_snap
            out["hit"] = hit_snap
            out["served"] = len(hit_body)

        _serve(options, fn)
        return out

    def test_local_hit_books_exactly_one_copy(self):
        got = self._hit_snapshot(ServerOptions(cache_result_mb=16.0))
        hit = got["hit"]
        assert set(hit["copies"]) == {"ingress", "cache_hit"}
        assert hit["copies"]["cache_hit"] == 1
        assert hit["bytes"]["cache_hit"] == got["served"]
        # the miss ran the pipeline: decode and encode booked real bytes
        assert got["miss"]["bytes"].get("decode", 0) > 0
        assert got["miss"]["bytes"].get("encode", 0) > 0

    def test_shm_hit_books_exactly_one_copy(self, tmp_path, monkeypatch):
        from imaginary_tpu.fleet import shmcache

        monkeypatch.setattr(shmcache, "default_path",
                            lambda: str(tmp_path / "shm"))
        got = self._hit_snapshot(ServerOptions(fleet_cache_mb=4.0))
        hit = got["hit"]
        assert set(hit["copies"]) == {"ingress", "cache_hit"}
        assert hit["copies"]["cache_hit"] == 1
        assert hit["bytes"]["cache_hit"] == got["served"]

    def test_tier_parity(self, tmp_path, monkeypatch):
        from imaginary_tpu.fleet import shmcache

        local = self._hit_snapshot(ServerOptions(cache_result_mb=16.0))
        monkeypatch.setattr(shmcache, "default_path",
                            lambda: str(tmp_path / "shm"))
        shm = self._hit_snapshot(ServerOptions(fleet_cache_mb=4.0))
        # identical stage sets, identical copy counts, identical body
        # bytes per hit: the tiers are indistinguishable to the ledger
        assert local["hit"]["copies"] == shm["hit"]["copies"]
        assert local["hit"]["bytes"] == shm["hit"]["bytes"]


class TestStreamingIngress413BeforeRead:
    def test_raw_declared_oversize_never_touches_body(self):
        from imaginary_tpu.web import sources

        class _NeverRead:
            @property
            def content(self):  # pragma: no cover - the assertion IS the test
                raise AssertionError(
                    "413-before-read: body stream was touched")

        class _Req(_NeverRead):
            content_length = sources.MAX_BODY_SIZE + 1
            headers = {"Content-Type": "image/jpeg"}

        with pytest.raises(ImageError) as ei:
            asyncio.run(sources.BodyImageSource()._read_raw(_Req()))
        assert ei.value.code == 413

    def test_multipart_part_header_oversize_is_413(self):
        # a part whose OWN Content-Length header declares more than the
        # cap is refused from the header alone — the (tiny) actual body
        # proves no read loop ran to find out
        from imaginary_tpu.web import sources

        boundary = "itpu-test-boundary"
        part = (f"--{boundary}\r\n"
                f"Content-Disposition: form-data; name=\"file\"; "
                f"filename=\"x.jpg\"\r\n"
                f"Content-Type: image/jpeg\r\n"
                f"Content-Length: {sources.MAX_BODY_SIZE + 1}\r\n"
                f"\r\n").encode() + b"tiny" + f"\r\n--{boundary}--\r\n".encode()

        async def fn(client):
            res = await client.post(
                "/resize?width=50&height=50", data=part,
                headers={"Content-Type":
                         f"multipart/form-data; boundary={boundary}"})
            assert res.status == 413, await res.text()

        _serve(ServerOptions(), fn)

    def test_within_cap_raw_body_still_serves(self):
        buf = fixture_bytes("imaginary.jpg")

        async def fn(client):
            snap, body = await _resize(client, buf)
            # streaming ingress books the upload exactly once
            assert snap["copies"].get("ingress") == 1
            assert snap["bytes"]["ingress"] == len(buf)
            im = Image.open(io.BytesIO(body))
            assert (im.width, im.height) == (120, 80)

        _serve(ServerOptions(), fn)


class TestCodecArena:
    @pytest.fixture(autouse=True)
    def _needs_arena(self):
        from imaginary_tpu.codecs import native_backend

        if native_backend.arena_stats() is None:
            pytest.skip("native codec arena not built")
        native_backend.set_arena_cap(0.0)
        yield
        native_backend.set_arena_cap(0.0)

    def test_scratch_reused_across_calls(self):
        from imaginary_tpu.codecs import native_backend

        rng = np.random.default_rng(3)
        arr = rng.integers(0, 256, (240, 320, 3), dtype=np.uint8)
        a = native_backend.resize_separable(arr, 120, 160, "lanczos3")
        before = native_backend.arena_stats()
        b = native_backend.resize_separable(arr, 120, 160, "lanczos3")
        after = native_backend.arena_stats()
        # the second identical call allocates nothing new: every slot
        # grab is a reuse, the live-byte gauge is flat
        assert after["reuses"] > before["reuses"]
        assert after["misses"] == before["misses"]
        assert after["bytes"] == before["bytes"]
        assert np.array_equal(a, b)

    def test_cap_evicts_oversize_scratch(self):
        from imaginary_tpu.codecs import native_backend

        rng = np.random.default_rng(4)
        arr = rng.integers(0, 256, (240, 320, 3), dtype=np.uint8)
        native_backend.resize_separable(arr, 120, 160, "lanczos3")
        assert native_backend.set_arena_cap(0.001)
        before = native_backend.arena_stats()
        out = native_backend.resize_separable(arr, 120, 160, "lanczos3")
        after = native_backend.arena_stats()
        # over-budget thread scratch is swap-freed after the call; the
        # output is unaffected
        assert after["evictions"] > before["evictions"]
        assert after["cap_bytes"] == int(0.001 * 1024 * 1024)
        assert out.shape == (120, 160, 3)


class TestDctShrinkOnLoadSpill:
    def test_host_spill_matches_full_decode_chain(self):
        """The dct shrink-on-load host path must reproduce the full
        decode + resample output (the spill behavior it replaces) within
        codec tolerance on a real baseline JPEG."""
        from imaginary_tpu import pipeline
        from imaginary_tpu.engine import host_exec
        from imaginary_tpu.options import ImageOptions

        buf = fixture_bytes("imaginary.jpg")
        o = ImageOptions(width=80, height=0, type="jpeg")
        runner = lambda a, p: host_exec.run(a, p)
        assert host_exec.dct_spill_enabled()
        was = pipeline.transport_dct_enabled()
        pipeline.set_transport_dct(True)
        try:
            dct = pipeline.process_operation("thumbnail", buf, o,
                                             runner=runner)
        finally:
            pipeline.set_transport_dct(was)
        full = pipeline.process_operation("thumbnail", buf, o,
                                          runner=runner)
        a = np.asarray(Image.open(io.BytesIO(bytes(dct.body))).convert("RGB"),
                       dtype=np.float64)
        b = np.asarray(Image.open(io.BytesIO(bytes(full.body))).convert("RGB"),
                       dtype=np.float64)
        assert a.shape == b.shape
        mse = float(np.mean((a - b) ** 2))
        psnr = 10.0 * np.log10(255.0 * 255.0 / max(mse, 1e-9))
        assert psnr >= 30.0, f"dct spill diverged: {psnr:.1f} dB"

    def test_spill_switch_rejects_dct_plans_when_off(self):
        from imaginary_tpu.engine import host_exec
        from imaginary_tpu.ops.plan import plan_operation, wrap_plan_dct
        from imaginary_tpu.options import ImageOptions

        plan = plan_operation("thumbnail", ImageOptions(width=64),
                              128, 128, 1, 3)
        wrapped = wrap_plan_dct(plan, 1024, 1024, 8, layout="420")
        assert host_exec.can_execute(wrapped)
        host_exec.set_dct_spill(False)
        try:
            assert not host_exec.can_execute(wrapped)
        finally:
            host_exec.set_dct_spill(True)
