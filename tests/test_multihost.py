"""Multi-host scale-out (ISSUE 20): host identity/epochs, peer gossip,
cross-host rendezvous routing, pressure spillover, and the /fleetz
cluster view.

The table/router tests are pure-unit with injected clocks, fetches and
hops (every rung of the fail-open ladder runs without a socket); the
HTTP tests pin the OFF-state byte parity and run a real two-app
cross-host forward over live aiohttp servers. The full two-SUPERVISOR
cluster (separate processes, admin planes, gossip over real sockets)
rides the slow e2e test here and chaos row 13 in `make chaos`.
"""

import asyncio
import io
import json
import os
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from imaginary_tpu import cache as cache_mod
from imaginary_tpu import failpoints
from imaginary_tpu.fleet import multihost as mh
from imaginary_tpu.fleet import router as router_mod
from imaginary_tpu.fleet import shmcache
from imaginary_tpu.fleet.shmcache import ShmCache
from imaginary_tpu.obs import aggregate as agg
from imaginary_tpu.obs import trace as obs_trace
from imaginary_tpu.web.config import ServerOptions
from tests.conftest import fixture_bytes

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _fixtures(testdata):
    return testdata


@pytest.fixture(autouse=True)
def _clean_host_env():
    """The identity helpers stamp os.environ (the worker-inherit
    contract); every test starts and ends unstamped so armed-state
    leakage can never fake parity elsewhere in the suite."""
    for env in (mh.HOST_ID_ENV, mh.HOST_EPOCH_ENV):
        os.environ.pop(env, None)
    yield
    for env in (mh.HOST_ID_ENV, mh.HOST_EPOCH_ENV):
        os.environ.pop(env, None)


def _host_payload(hid="peer-b", epoch=5, serve="http://127.0.0.1:1",
                  workers=2, queue=3.0, plevel=0):
    return {"host": {"id": hid, "epoch": epoch, "serve_url": serve,
                     "workers_alive": workers, "est_queue_ms": queue,
                     "pressure_level": plevel}}


# --- --peers grammar ---------------------------------------------------------


class TestParsePeers:
    def test_csv_whitespace_scheme_default_dedup(self):
        got = mh.parse_peers(
            " 10.0.0.2:9101, http://10.0.0.3:9101/ \n 10.0.0.2:9101")
        assert got == ["http://10.0.0.2:9101", "http://10.0.0.3:9101"]

    def test_at_file_with_comments(self, tmp_path):
        f = tmp_path / "peers.txt"
        f.write_text("# fleet\nhttp://a:1\n\nb:2  # second host\n")
        assert mh.parse_peers("@" + str(f)) == ["http://a:1", "http://b:2"]

    def test_unreadable_file_refuses(self, tmp_path):
        with pytest.raises(ValueError):
            mh.parse_peers("@" + str(tmp_path / "missing.txt"))

    def test_empty_spec(self):
        assert mh.parse_peers("") == []
        assert mh.parse_peers("  ,  ") == []


# --- host identity & epochs --------------------------------------------------


class TestHostIdentity:
    def test_unarmed_reads_empty(self):
        assert mh.host_id() == ""
        assert mh.host_epoch() == 0

    def test_mint_strictly_greater_across_restarts(self):
        t = [1000.0]
        first = mh.mint_host_epoch(clock=lambda: t[0])
        t[0] += 0.001  # even one ms later
        assert mh.mint_host_epoch(clock=lambda: t[0]) > first

    def test_ensure_stamps_once_and_children_inherit(self):
        hid, epoch = mh.ensure_host_identity("host-a",
                                             clock=lambda: 1234.5)
        assert (hid, epoch) == ("host-a", 1234500)
        assert os.environ[mh.HOST_ID_ENV] == "host-a"
        # re-entry (a worker re-running main) keeps the incarnation:
        # a worker must never mint its own host epoch
        hid2, epoch2 = mh.ensure_host_identity("other",
                                               clock=lambda: 9999.0)
        assert (hid2, epoch2) == ("host-a", 1234500)

    def test_default_id_is_hostname(self):
        import socket

        hid, _ = mh.ensure_host_identity("")
        assert hid == socket.gethostname()

    def test_garbage_epoch_env_reads_zero(self):
        os.environ[mh.HOST_EPOCH_ENV] = "not-a-number"
        assert mh.host_epoch() == 0


# --- host rendezvous ---------------------------------------------------------


class TestRendezvousHost:
    def test_deterministic_and_all_hosts_used(self):
        hosts = ["h1", "h2", "h3"]
        keys = [b"k%d" % i for i in range(300)]
        owners = [mh.rendezvous_host(hosts, k) for k in keys]
        assert owners == [mh.rendezvous_host(hosts, k) for k in keys]
        assert set(owners) == set(hosts)

    def test_minimal_disruption_on_host_leave(self):
        keys = [b"d%d" % i for i in range(300)]
        before = {k: mh.rendezvous_host(["h1", "h2", "h3"], k)
                  for k in keys}
        after = {k: mh.rendezvous_host(["h1", "h3"], k) for k in keys}
        for k in keys:
            if before[k] != "h2":
                assert after[k] == before[k]
            else:
                assert after[k] in ("h1", "h3")

    def test_empty_is_none(self):
        assert mh.rendezvous_host([], b"x") is None


# --- peer table --------------------------------------------------------------


class TestPeerTable:
    def test_failed_poll_marks_dead_immediately(self):
        t = mh.PeerTable(["http://p:1"], clock=lambda: 100.0)
        t.observe("http://p:1", _host_payload())
        assert len(t.alive()) == 1
        t.observe("http://p:1", None)
        p = t.peers()[0]
        assert not p.alive and p.failures == 1
        assert t.alive() == []

    def test_staleness_is_a_read_side_judgement(self):
        now = [100.0]
        t = mh.PeerTable(["http://p:1"], staleness_s=5.0,
                         clock=lambda: now[0])
        t.observe("http://p:1", _host_payload())
        assert len(t.alive()) == 1
        now[0] += 20.0  # gossip wedged: no observe() ever marked it dead
        assert t.alive() == []
        assert t.lookup("peer-b") is None

    def test_epoch_bump_counts_restarts(self):
        t = mh.PeerTable(["http://p:1"], clock=lambda: 1.0)
        t.observe("http://p:1", _host_payload(epoch=5))
        t.observe("http://p:1", _host_payload(epoch=5))
        assert t.peers()[0].epoch_bumps == 0
        t.observe("http://p:1", _host_payload(epoch=9))
        assert t.peers()[0].epoch_bumps == 1

    def test_least_loaded_skips_critical_peers(self):
        from imaginary_tpu.engine.pressure import LEVEL_CRITICAL

        t = mh.PeerTable(["http://a:1", "http://b:1"], clock=lambda: 1.0)
        t.observe("http://a:1", _host_payload(hid="a", queue=1.0,
                                              plevel=LEVEL_CRITICAL))
        t.observe("http://b:1", _host_payload(hid="b", queue=50.0))
        got = t.least_loaded()
        assert got is not None and got.host_id == "b"
        t.observe("http://b:1", _host_payload(hid="b", queue=50.0,
                                              plevel=LEVEL_CRITICAL))
        assert t.least_loaded() is None

    def test_lookup_by_host_id(self):
        t = mh.PeerTable(["http://a:1"], clock=lambda: 1.0)
        t.observe("http://a:1", _host_payload(hid="a"))
        assert t.lookup("a").base == "http://a:1"
        assert t.lookup("nobody") is None


# --- gossip ------------------------------------------------------------------


class TestGossip:
    def test_poll_once_injectable_fetch(self):
        t = mh.PeerTable(["http://good:1", "http://bad:1"],
                         clock=lambda: 1.0)

        def fetch(url, timeout):
            assert timeout == mh.PEER_PROBE_TIMEOUT_S
            if "good" in url:
                return json.dumps(_host_payload(hid="g"))
            return "not json {{{"

        g = mh.GossipAgent(t, fetch=fetch)
        g.poll_once()
        assert g.polls == 1
        by = {p.base: p for p in t.peers()}
        assert by["http://good:1"].alive
        assert not by["http://bad:1"].alive

    def test_peer_health_failpoint_marks_dead(self):
        t = mh.PeerTable(["http://p:1"], clock=lambda: 1.0)
        g = mh.GossipAgent(
            t, fetch=lambda u, to: json.dumps(_host_payload()))
        failpoints.activate("peer.health=error")
        try:
            g.poll_once()
        finally:
            failpoints.deactivate()
        assert t.alive() == []
        g.poll_once()  # disarmed: the peer answers again
        assert len(t.alive()) == 1


# --- router: route decision + the fail-open hop ladder ----------------------


def _router(table=None, **kw):
    table = table or mh.PeerTable(["http://b:1"], clock=lambda: 1.0)
    kw.setdefault("self_id", "host-a")
    kw.setdefault("self_epoch", 100)
    kw.setdefault("route_all", True)
    return router_mod.HostRouter(table, **kw)


def _owned_key(r, owner):
    for i in range(2000):
        k = b"key-%d" % i
        if r.owner_host(k) == owner:
            return k
    raise AssertionError("no key owned by " + owner)


def _ok_headers(peer):
    return {router_mod.HOST_EPOCH_HEADER:
            f"{peer.host_id}:{peer.host_epoch}",
            "Content-Type": "image/jpeg",
            "X-Imaginary-Backend": "tpu"}


class TestRouteDecision:
    def test_ladder(self):
        r = _router()
        r.table.observe("http://b:1", _host_payload(hid="host-b"))
        k = _owned_key(r, "host-b")
        # hop marker: arrived over the wire, must run locally
        assert r.route_target({router_mod.ROUTE_HEADER: "fwd=x"}, k) is None
        assert r.stats.served_for_peer == 0  # route_target doesn't book it
        assert r.note_hop_marker({router_mod.ROUTE_HEADER: "fwd=x"})
        assert r.stats.served_for_peer == 1
        # client pin
        assert r.route_target({router_mod.ROUTE_HEADER: "local"}, k) is None
        # owned by the peer: forwarded
        assert r.route_target({}, k).host_id == "host-b"
        # self-owned keys stay local
        assert r.route_target({}, _owned_key(r, "host-a")) is None

    def test_router_off_requires_hint(self):
        r = _router(route_all=False)
        r.table.observe("http://b:1", _host_payload(hid="host-b"))
        k = _owned_key(r, "host-b")
        assert r.route_target({}, k) is None
        assert r.route_target({router_mod.ROUTE_HEADER: "route"},
                              k).host_id == "host-b"

    def test_single_host_cluster_never_routes(self):
        r = _router()  # peer never observed: table has no alive entry
        assert r.owner_host(b"anything") is None
        assert r.route_target({}, b"anything") is None

    def test_dead_owner_falls_back_local(self):
        now = [1.0]
        t = mh.PeerTable(["http://b:1"], staleness_s=5.0,
                         clock=lambda: now[0])
        r = _router(table=t)
        t.observe("http://b:1", _host_payload(hid="host-b"))
        k = _owned_key(r, "host-b")
        assert r.route_target({}, k) is not None
        # rendezvous still elects host-b from the last-known membership,
        # but gossip can no longer vouch for it -> local, counted
        t.observe("http://b:1", None)
        assert r.route_target({}, k) is None


class TestForwardLadder:
    def _peer(self, r):
        r.table.observe("http://b:1",
                        _host_payload(hid="host-b", epoch=7,
                                      serve="http://b:2"))
        return r.table.lookup("host-b")

    def test_success_returns_processed_image(self):
        calls = {}

        async def hop(method, url, body, headers, timeout):
            calls.update(method=method, url=url, body=body,
                         headers=headers, timeout=timeout)
            return 200, _ok_headers(self._peer(r)), b"JPEGBYTES"

        r = _router(hop=hop)
        peer = self._peer(r)
        got = asyncio.run(r.try_forward(
            peer, "resize", {"width": "100"}, b"src", "image/jpeg"))
        assert got is not None
        out, placement = got
        assert bytes(out.body) == b"JPEGBYTES"
        assert out.mime == "image/jpeg"
        assert placement == "tpu"
        assert r.stats.forwards == 1
        assert calls["method"] == "POST"
        assert calls["url"].startswith("http://b:2/resize?")
        assert calls["headers"][router_mod.ROUTE_HEADER] == "fwd=host-a"
        assert 0 < calls["timeout"] <= r.hop_s

    def test_non_200_fails_open(self):
        async def hop(*a, **kw):
            return 503, {}, b"shed"

        r = _router(hop=hop)
        peer = self._peer(r)
        assert asyncio.run(r.try_forward(peer, "resize", {}, b"s",
                                         "image/jpeg")) is None
        assert r.stats.forward_fails == 1

    def test_hop_exception_fails_open(self):
        async def hop(*a, **kw):
            raise OSError("connection refused")

        r = _router(hop=hop)
        peer = self._peer(r)
        assert asyncio.run(r.try_forward(peer, "resize", {}, b"s",
                                         "image/jpeg")) is None
        assert r.stats.forward_fails == 1

    def test_stale_host_epoch_answer_is_fenced(self):
        async def hop(*a, **kw):
            return 200, {router_mod.HOST_EPOCH_HEADER: "host-b:3",
                         "Content-Type": "image/jpeg"}, b"old"

        r = _router(hop=hop)
        peer = self._peer(r)  # gossip knows epoch 7; the answer says 3
        assert asyncio.run(r.try_forward(peer, "resize", {}, b"s",
                                         "image/jpeg")) is None
        assert r.stats.fenced_answers == 1
        assert r.stats.forwards == 0

    def test_missing_epoch_stamp_is_fenced(self):
        async def hop(*a, **kw):
            return 200, {"Content-Type": "image/jpeg"}, b"x"

        r = _router(hop=hop)
        peer = self._peer(r)
        assert asyncio.run(r.try_forward(peer, "resize", {}, b"s",
                                         "image/jpeg")) is None
        assert r.stats.fenced_answers == 1

    def test_exhausted_deadline_never_dials(self):
        async def hop(*a, **kw):
            raise AssertionError("dialed with no budget")

        r = _router(hop=hop)
        peer = self._peer(r)
        from imaginary_tpu import deadline as deadline_mod

        tr = obs_trace.RequestTrace(request_id="t", enabled=False)
        tr.deadline = deadline_mod.Deadline(0.001,
                                            t0=time.monotonic() - 1.0)
        token = obs_trace.activate(tr)
        try:
            got = asyncio.run(r.try_forward(peer, "resize", {}, b"s",
                                            "image/jpeg"))
        finally:
            obs_trace.deactivate(token)
        assert got is None
        assert r.stats.forward_fails == 1

    def test_deadline_clamps_hop_budget(self):
        seen = {}

        async def hop(method, url, body, headers, timeout):
            seen["timeout"] = timeout
            return 200, _ok_headers(self._peer(r)), b"x"

        r = _router(hop=hop, hop_s=30.0)
        peer = self._peer(r)
        from imaginary_tpu import deadline as deadline_mod

        tr = obs_trace.RequestTrace(request_id="t", enabled=False)
        tr.deadline = deadline_mod.Deadline(0.5)
        token = obs_trace.activate(tr)
        try:
            asyncio.run(r.try_forward(peer, "resize", {}, b"s",
                                      "image/jpeg"))
        finally:
            obs_trace.deactivate(token)
        assert seen["timeout"] <= 0.5

    def test_peer_forward_failpoint_fails_open_without_dialing(self):
        async def hop(*a, **kw):
            raise AssertionError("failpoint must fire before the dial")

        r = _router(hop=hop)
        peer = self._peer(r)
        failpoints.activate("peer.forward[host-b]=error")
        try:
            got = asyncio.run(r.try_forward(peer, "resize", {}, b"s",
                                            "image/jpeg"))
        finally:
            failpoints.deactivate()
        assert got is None
        assert r.stats.forward_fails == 1


class TestSpillover:
    def test_spill_target_is_least_loaded_noncritical(self):
        from imaginary_tpu.engine.pressure import LEVEL_CRITICAL

        t = mh.PeerTable(["http://b:1", "http://c:1"], clock=lambda: 1.0)
        r = _router(table=t)
        assert r.spill_target() is None  # nobody alive yet
        t.observe("http://b:1", _host_payload(hid="b", queue=9.0))
        t.observe("http://c:1", _host_payload(hid="c", queue=2.0))
        assert r.spill_target().host_id == "c"
        t.observe("http://c:1", _host_payload(hid="c", queue=2.0,
                                              plevel=LEVEL_CRITICAL))
        assert r.spill_target().host_id == "b"

    def test_try_spill_roundtrip_and_fail_open(self):
        async def ok_hop(method, url, body, headers, timeout):
            assert method == "GET"
            assert url == "http://b:2/resize?width=9&url=x"
            assert headers[router_mod.ROUTE_HEADER] == "fwd=host-a"
            return 200, _ok_headers(peer), b"BODY"

        r = _router(hop=ok_hop)
        r.table.observe("http://b:1",
                        _host_payload(hid="host-b", epoch=7,
                                      serve="http://b:2"))
        peer = r.table.lookup("host-b")
        got = asyncio.run(r.try_spill(peer, "GET",
                                      "/resize?width=9&url=x", b"",
                                      {"Accept": "image/webp"}))
        assert got == (200, "image/jpeg", b"BODY")
        assert r.stats.spills == 1

        async def shed_hop(*a, **kw):
            return 503, {}, b"shed there too"

        r2 = _router(hop=shed_hop)
        r2.table.observe("http://b:1",
                         _host_payload(hid="host-b", serve="http://b:2"))
        peer2 = r2.table.lookup("host-b")
        assert asyncio.run(r2.try_spill(peer2, "GET", "/x", b"",
                                        {})) is None
        assert r2.stats.spill_fails == 1


# --- shm host epoch ----------------------------------------------------------


class TestShmHostEpoch:
    def test_stamp_roundtrip_and_host_fencing(self, tmp_path):
        path = str(tmp_path / "fleet.shm")
        sup = ShmCache(path, create=True, size_mb=1.0, owner=True)
        try:
            assert sup.host_epoch_stamp() == 0
            assert not sup.host_fenced()  # unarmed: never fenced
            sup.stamp_host_epoch(500)
            assert sup.host_epoch_stamp() == 500
            # this process was born into incarnation 400: deposed
            os.environ[mh.HOST_EPOCH_ENV] = "400"
            assert sup.host_fenced()
            # the current incarnation (or a newer one) is never fenced
            os.environ[mh.HOST_EPOCH_ENV] = "500"
            assert not sup.host_fenced()
        finally:
            sup.close()

    def test_creator_stamps_armed_host_epoch(self, tmp_path):
        os.environ[mh.HOST_EPOCH_ENV] = "777"
        path = str(tmp_path / "fleet2.shm")
        sup = ShmCache(path, create=True, size_mb=1.0, owner=True)
        try:
            assert sup.host_epoch_stamp() == 777
        finally:
            sup.close()


# --- /fleetz host block + cluster view ---------------------------------------


class TestFleetzCluster:
    def test_build_fleetz_host_block_rollup(self):
        view = {0: {"pid": 1, "alive": True, "epoch": 1},
                1: {"pid": 2, "alive": False, "epoch": 1}}
        health = {0: {"estimatedQueueMs": 12.5,
                      "pressure": {"state": 1}}}
        out = agg.build_fleetz(view, health, set(),
                               host={"id": "h-a", "epoch": 9,
                                     "serve_url": "http://h-a:1"})
        assert out["host"] == {"id": "h-a", "epoch": 9,
                               "serve_url": "http://h-a:1",
                               "workers_alive": 1, "est_queue_ms": 12.5,
                               "pressure_level": 1}
        # parity: no host argument, no host block
        assert "host" not in agg.build_fleetz(view, health, set())

    def test_cluster_view_merges_local_and_peers(self):
        t = mh.PeerTable(["http://b:1", "http://c:1"], clock=lambda: 1.0)
        t.observe("http://b:1", _host_payload(hid="b", epoch=4))
        # c never answered: appears dead, fleetz withheld
        local = agg.build_fleetz({}, {}, set(),
                                 host={"id": "a", "epoch": 2,
                                       "serve_url": "u"})
        out = mh.build_cluster_view(local, t)
        assert out["scope"] == "cluster"
        assert out["hosts"]["a"]["local"] is True
        assert out["hosts"]["b"]["alive"] is True
        assert out["peers"]["http://b:1"]["fleetz"] is not None
        assert out["peers"]["http://c:1"]["fleetz"] is None
        assert out["local"] is local


# --- HTTP: parity, surfaces, live cross-host forward -------------------------


def run(options, fn):
    async def runner():
        from imaginary_tpu.web.app import create_app

        app = create_app(options, log_stream=io.StringIO())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await fn(client, app)
        finally:
            await client.close()

    asyncio.run(runner())


def jpg() -> bytes:
    return fixture_bytes("imaginary.jpg")


def _post_kw():
    return {"data": jpg(), "headers": {"Content-Type": "image/jpeg"}}


class TestMultihostHttp:
    def test_peers_off_byte_parity(self):
        os.environ.pop(shmcache.PATH_ENV, None)
        bodies = {}

        async def baseline(client, app):
            r = await client.post("/resize?width=140", **_post_kw())
            bodies["off"] = await r.read()
            assert router_mod.HOST_EPOCH_HEADER not in r.headers
            h = await (await client.get("/health")).json()
            assert "multihost" not in h and "host" not in h
            assert app["service"].multihost is None
            # no peers = no identity stamps, no gossip thread
            assert mh.host_id() == ""
            assert not any(t.name == "peer-gossip"
                           for t in __import__("threading").enumerate())

        async def armed(client, app):
            r = await client.post("/resize?width=140", **_post_kw())
            bodies["on"] = await r.read()
            svc = app["service"]
            assert r.headers[router_mod.HOST_EPOCH_HEADER] == \
                svc.multihost.identity_header
            h = await (await client.get("/health")).json()
            assert h["host"]["id"] == "parity-host"
            assert h["multihost"]["host_id"] == "parity-host"
            assert h["multihost"]["router"] is False

        run(ServerOptions(), baseline)
        run(ServerOptions(peers="http://127.0.0.1:1",
                          host_id="parity-host"), armed)
        assert bodies["off"] == bodies["on"]

    def test_unreachable_peer_fails_open(self):
        # --router armed, the only peer dead: every request runs local,
        # same bytes, no new error class
        async def armed(client, app):
            r = await client.post("/resize?width=133", **_post_kw())
            assert r.status == 200
            h = await (await client.get("/health")).json()
            assert h["multihost"]["forwards"] == 0

        run(ServerOptions(peers="http://127.0.0.1:1", router=True,
                          host_id="solo"), armed)

    def test_forward_e2e_between_two_hosts(self):
        # two real apps, distinct host identities, routing armed on A:
        # a request for a digest B owns takes one real HTTP hop and
        # serves B's bytes; B books served_for_peer and never re-routes
        async def fn():
            from imaginary_tpu.web.app import create_app

            def boot(hid):
                os.environ[mh.HOST_ID_ENV] = hid
                os.environ[mh.HOST_EPOCH_ENV] = str(100)
                try:
                    return create_app(
                        ServerOptions(peers="http://127.0.0.1:1",
                                      router=True, host_id=hid,
                                      fleet_hop_ms=15000.0),
                        log_stream=io.StringIO())
                finally:
                    os.environ.pop(mh.HOST_ID_ENV, None)
                    os.environ.pop(mh.HOST_EPOCH_ENV, None)

            app_a, app_b = boot("host-a"), boot("host-b")
            ca = TestClient(TestServer(app_a))
            cb = TestClient(TestServer(app_b))
            await ca.start_server()
            await cb.start_server()
            try:
                ra = app_a["service"].multihost
                rb = app_b["service"].multihost
                # cross-teach the tables by hand (gossip would need two
                # admin planes; the table API is the contract)
                ra.table.observe(
                    "http://127.0.0.1:1",
                    _host_payload(hid="host-b", epoch=100,
                                  serve=str(cb.make_url("")).rstrip("/")))
                body = jpg()
                digest = cache_mod.source_digest(body)
                from imaginary_tpu.params import build_params_from_query

                width = None
                for cand in range(60, 300):
                    opts = build_params_from_query({"width": str(cand)})
                    skey = cache_mod.shared_key(
                        cache_mod.request_key(digest, "resize", opts))
                    if ra.owner_host(skey) == "host-b":
                        width = cand
                        break
                assert width is not None
                fwd = await ca.post(f"/resize?width={width}", **_post_kw())
                assert fwd.status == 200
                assert fwd.headers[router_mod.HOST_EPOCH_HEADER] == \
                    "host-a:100"
                b_fwd = await fwd.read()
                assert ra.stats.forwards == 1
                assert rb.stats.served_for_peer == 1
                assert rb.stats.forwards == 0  # one hop, ever
                direct = await cb.post(f"/resize?width={width}",
                                       **_post_kw())
                assert await direct.read() == b_fwd
            finally:
                await ca.close()
                await cb.close()

        asyncio.run(fn())

    def test_spillover_offers_before_shedding(self):
        # force A's governor critical (memory.rss chaos site) and point
        # its table at a healthy B: batch-class work that would 503 on A
        # ships to B and answers 200; with B critical too, A sheds the
        # 503 the request was owed anyway (no ping-pong)
        qos_cfg = json.dumps({
            "default": {"class": "standard"},
            "tenants": [{"name": "bulk", "class": "batch",
                         "api_keys": ["bulk-key"]}],
        })

        async def fn():
            from imaginary_tpu.web.app import create_app

            def boot(hid, pressure):
                os.environ[mh.HOST_ID_ENV] = hid
                os.environ[mh.HOST_EPOCH_ENV] = "100"
                try:
                    o = ServerOptions(
                        peers="http://127.0.0.1:1", host_id=hid,
                        fleet_hop_ms=15000.0, qos_config=qos_cfg,
                        pressure_rss_mb=1_000_000.0 if pressure else 0.0)
                    return create_app(o, log_stream=io.StringIO())
                finally:
                    os.environ.pop(mh.HOST_ID_ENV, None)
                    os.environ.pop(mh.HOST_EPOCH_ENV, None)

            app_a, app_b = boot("host-a", True), boot("host-b", False)
            ca = TestClient(TestServer(app_a))
            cb = TestClient(TestServer(app_b))
            await ca.start_server()
            await cb.start_server()
            try:
                svc_a = app_a["service"]
                ra = svc_a.multihost
                serve_b = str(cb.make_url("")).rstrip("/")
                ra.table.observe(
                    "http://127.0.0.1:1",
                    _host_payload(hid="host-b", epoch=100,
                                  serve=serve_b))
                svc_a.pressure.config.sample_interval_s = 0.0
                failpoints.activate("memory.rss=error")
                try:
                    from imaginary_tpu.engine.pressure import \
                        LEVEL_CRITICAL

                    assert svc_a.pressure.level() == LEVEL_CRITICAL
                    r = await ca.post("/resize?width=123&key=bulk-key",
                                      **_post_kw())
                    assert r.status == 200  # spilled, not shed
                    assert ra.stats.spills == 1
                    rb = app_b["service"].multihost
                    assert rb.stats.served_for_peer >= 1
                    assert rb.stats.spills == 0  # marker blocks re-spill
                    # B at critical too: no spill target, A sheds 503
                    ra.table.observe(
                        "http://127.0.0.1:1",
                        _host_payload(hid="host-b", epoch=100,
                                      plevel=LEVEL_CRITICAL,
                                      serve=serve_b))
                    r2 = await ca.post("/resize?width=124&key=bulk-key",
                                       **_post_kw())
                    assert r2.status == 503
                    assert "Retry-After" in r2.headers
                finally:
                    failpoints.deactivate()
            finally:
                await ca.close()
                await cb.close()

        asyncio.run(fn())


# --- two real supervisors (subprocess e2e) -----------------------------------


@pytest.mark.slow
def test_two_supervisor_cluster_forward():
    """The full stack, no shortcuts: two `python -m imaginary_tpu.cli`
    clusters on one machine, each a supervisor + worker with its own
    admin plane, cross-pointed --peers, --router on. Gossip learns the
    peer over real sockets; a digest owned by the other host takes a
    real cross-host hop."""
    import subprocess
    import sys
    import urllib.request

    import bench_util

    ports = [bench_util.free_port() for _ in range(4)]
    sp_a, sp_b, ad_a, ad_b = ports
    env = dict(os.environ)
    env.pop(mh.HOST_ID_ENV, None)
    env.pop(mh.HOST_EPOCH_ENV, None)
    env.pop(shmcache.PATH_ENV, None)
    env["JAX_PLATFORMS"] = "cpu"

    # two workers per host: the supervisor path (admin plane, shm fleet
    # cache, gossip thread) is exactly what production multi-host runs
    def start_host(hid, port, admin, peer_admin):
        e = dict(env)
        return subprocess.Popen(
            [sys.executable, "-m", "imaginary_tpu.cli", "--workers", "2",
             "--port", str(port), "--host-id", hid,
             "--peers", f"http://127.0.0.1:{peer_admin}",
             "--router", "--fleet-hop-ms", "15000",
             "--peer-probe-interval", "0.3",
             "--fleet-cache-mb", "8", "--fleet-admin-port", str(admin),
             "--cache-result-mb", "8"],
            env=e, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)

    pa = start_host("host-a", sp_a, ad_a, ad_b)
    pb = start_host("host-b", sp_b, ad_b, ad_a)
    try:
        def wait_http(url, deadline=90.0):
            t0 = time.monotonic()
            while time.monotonic() - t0 < deadline:
                try:
                    with urllib.request.urlopen(url, timeout=2.0) as r:
                        return json.loads(r.read().decode())
                except Exception:
                    time.sleep(0.3)
            raise AssertionError("never healthy: " + url)

        ha = wait_http(f"http://127.0.0.1:{sp_a}/health")
        wait_http(f"http://127.0.0.1:{sp_b}/health")
        assert ha["host"]["id"] == "host-a"
        # cluster view converges once gossip has crossed
        t0 = time.monotonic()
        cluster = {}
        while time.monotonic() - t0 < 30.0:
            cluster = wait_http(
                f"http://127.0.0.1:{ad_a}/fleetz?scope=cluster")
            if cluster.get("hosts", {}).get("host-b", {}).get("alive"):
                break
            time.sleep(0.5)
        assert cluster["hosts"]["host-b"]["alive"] is True
        assert cluster["hosts"]["host-a"]["local"] is True

        # worker gossip rides the same admin planes; give the workers a
        # beat to see host-b alive, then hunt a width A must forward
        body = fixture_bytes("imaginary.jpg")
        deadline = time.monotonic() + 45.0
        forwarded = False
        while time.monotonic() < deadline and not forwarded:
            for width in range(90, 130):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{sp_a}/resize?width={width}",
                    data=body, method="POST",
                    headers={"Content-Type": "image/jpeg",
                             "Connection": "close"})
                with urllib.request.urlopen(req, timeout=30.0) as r:
                    assert r.status == 200
            h = wait_http(f"http://127.0.0.1:{sp_a}/health")
            if h.get("multihost", {}).get("forwards", 0) > 0:
                forwarded = True
        assert forwarded, "no request ever took the cross-host hop"
    finally:
        import signal as _signal

        for p in (pa, pb):
            try:
                p.send_signal(_signal.SIGTERM)
            except ProcessLookupError:
                pass
        for p in (pa, pb):
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
