"""Observability layer (imaginary_tpu/obs/ + its web/engine threading).

Covers the ISSUE 3 acceptance list: X-Request-ID / traceparent
propagation (inbound passthrough, generation, outbound forwarding to
origins), histogram bucket monotonicity + _sum/_count consistency,
Server-Timing response header contents, /debugz gating (404 when
disabled, auth posture when enabled), the wide-event JSON schema, and a
STRICT Prometheus exposition-format parse of /metrics (HELP/TYPE per
family, grouped samples, escaped labels, no duplicate series).
"""

import asyncio
import io
import json
import re
import secrets

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from imaginary_tpu.obs import debugz as obs_debugz
from imaginary_tpu.obs import events as obs_events
from imaginary_tpu.obs import histogram as obs_hist
from imaginary_tpu.obs import trace as obs_trace
from imaginary_tpu.web.config import ServerOptions
from tests.conftest import fixture_bytes


@pytest.fixture(scope="module", autouse=True)
def _fixtures(testdata):
    return testdata


def run(options, fn, origin_handler=None, log_stream=None):
    """test_cache.py's harness: fn(client, origin_url, app) against a
    fresh app; optional captured log stream (access log + wide events)."""

    async def runner():
        from imaginary_tpu.web.app import create_app

        origin_url = None
        origin = None
        if origin_handler is not None:
            oapp = web.Application()
            oapp.router.add_route("*", "/{tail:.*}", origin_handler)
            origin = TestServer(oapp)
            await origin.start_server()
            origin_url = f"http://127.0.0.1:{origin.port}"

        app = create_app(options, log_stream=log_stream or io.StringIO())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await fn(client, origin_url, app)
        finally:
            await client.close()
            if origin is not None:
                await origin.close()

    asyncio.run(runner())


def jpg() -> bytes:
    return fixture_bytes("imaginary.jpg")


# --- trace unit behavior ------------------------------------------------------

class TestTraceUnit:
    def test_traceparent_inbound_parsed(self):
        tid, sid = secrets.token_hex(16), secrets.token_hex(8)
        tr = obs_trace.RequestTrace("rid", f"00-{tid}-{sid}-01")
        assert tr.trace_id == tid
        assert tr.parent_span_id == sid
        assert tr.traceparent().startswith(f"00-{tid}-")
        assert tr.traceparent().endswith("-01")

    def test_malformed_traceparent_starts_fresh_trace(self):
        for bad in ("", "garbage", "00-xyz-abc-01", "00-" + "0" * 31 + "-" +
                    "0" * 16 + "-01"):
            tr = obs_trace.RequestTrace("rid", bad)
            assert re.fullmatch(r"[0-9a-f]{32}", tr.trace_id)
            assert tr.parent_span_id == ""

    def test_outbound_traceparent_same_trace_new_span(self):
        tr = obs_trace.RequestTrace("rid")
        a, b = tr.outbound_traceparent(), tr.outbound_traceparent()
        assert a != b
        assert a.split("-")[1] == b.split("-")[1] == tr.trace_id

    def test_sanitize_request_id(self):
        assert obs_trace.sanitize_request_id("abc-123_X.y") == "abc-123_X.y"
        assert obs_trace.sanitize_request_id("") == ""
        assert obs_trace.sanitize_request_id("evil\nheader: x") == ""
        assert obs_trace.sanitize_request_id("x" * 200) == ""

    def test_server_timing_aggregates_repeated_spans(self):
        tr = obs_trace.RequestTrace("rid")
        tr.add_span("decode", 2.0)
        tr.add_span("decode", 3.0)
        tr.add_span("encode", 1.5)
        st = tr.server_timing()
        assert "decode;dur=5.00" in st
        assert "encode;dur=1.50" in st

    def test_span_context_manager_needs_active_trace(self):
        # no active trace: pure no-op, no error
        with obs_trace.span("x"):
            pass
        tr = obs_trace.RequestTrace("rid")
        token = obs_trace.activate(tr)
        try:
            with obs_trace.span("work"):
                pass
        finally:
            obs_trace.deactivate(token)
        assert [s.name for s in tr.spans] == ["work"]

    def test_disabled_trace_records_nothing(self):
        tr = obs_trace.RequestTrace("rid", enabled=False)
        tr.add_span("decode", 2.0)
        tr.annotate(op="resize")
        assert tr.spans == [] and tr.fields == {}


# --- histogram unit behavior --------------------------------------------------

class TestHistogramUnit:
    def test_bucket_monotonicity_and_sum_count(self):
        h = obs_hist.Histogram(buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5, 5.0, 0.05):
            h.observe(v)
        cumulative, total_sum, total_count = h.snapshot()
        assert cumulative == [1, 3, 4, 5]  # nondecreasing, +Inf == count
        assert total_count == 5
        assert abs(total_sum - 5.605) < 1e-9
        assert all(a <= b for a, b in zip(cumulative, cumulative[1:]))

    def test_boundary_value_lands_in_its_le_bucket(self):
        h = obs_hist.Histogram(buckets=(0.1, 1.0))
        h.observe(0.1)  # le="0.1" is INCLUSIVE (Prometheus semantics)
        cumulative, _, _ = h.snapshot()
        assert cumulative[0] == 1

    def test_label_escaping(self):
        assert obs_hist.escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'

    def test_vec_series_bound(self):
        vec = obs_hist.CounterVec(("k",))
        for i in range(obs_hist._MAX_SERIES + 10):
            vec.inc((f"v{i}",))
        assert len(vec.items()) <= obs_hist._MAX_SERIES + 1  # + overflow


# --- request identity over HTTP ----------------------------------------------

class TestRequestIdentity:
    def test_request_id_generated_on_every_response(self):
        async def fn(client, _origin, _app):
            for path in ("/health", "/metrics", "/bogus-route"):
                res = await client.get(path)
                rid = res.headers.get("X-Request-ID")
                assert rid and re.fullmatch(r"[0-9a-f]{32}", rid)

        run(ServerOptions(), fn)

    def test_inbound_request_id_passthrough(self):
        async def fn(client, _origin, _app):
            res = await client.get("/health",
                                   headers={"X-Request-ID": "my-id-123"})
            assert res.headers["X-Request-ID"] == "my-id-123"
            # hostile ids are regenerated, not echoed
            res = await client.get("/health",
                                   headers={"X-Request-ID": "x y\tz"})
            assert re.fullmatch(r"[0-9a-f]{32}",
                                res.headers["X-Request-ID"])

        run(ServerOptions(), fn)

    def test_outbound_fetch_forwards_trace_headers(self):
        seen = []

        async def origin(request):
            seen.append(dict(request.headers))
            return web.Response(body=jpg(), content_type="image/jpeg")

        tid = secrets.token_hex(16)

        async def fn(client, origin_url, _app):
            res = await client.get(
                f"/resize?width=100&url={origin_url}/img.jpg",
                headers={"traceparent": f"00-{tid}-{'ab' * 8}-01",
                         "X-Request-ID": "req-42"},
            )
            assert res.status == 200
            assert res.headers["X-Request-ID"] == "req-42"
            assert len(seen) == 1
            h = seen[0]
            assert h["X-Request-ID"] == "req-42"
            # same trace continues; the hop gets its own child span id
            parts = h["traceparent"].split("-")
            assert parts[1] == tid and parts[2] != "ab" * 8

        run(ServerOptions(enable_url_source=True), fn, origin_handler=origin)

    def test_trace_headers_do_not_partition_source_cache(self):
        hits = [0]

        async def origin(request):
            hits[0] += 1
            return web.Response(body=jpg(), content_type="image/jpeg")

        async def fn(client, origin_url, app):
            for _ in range(3):  # unique traceparent per request
                res = await client.get(
                    f"/resize?width=100&url={origin_url}/img.jpg")
                assert res.status == 200
            assert hits[0] == 1  # origin fetched once despite 3 traces
            assert app["service"].caches.stats.source_hits == 2

        run(ServerOptions(enable_url_source=True, cache_source_ttl=60.0),
            fn, origin_handler=origin)


# --- Server-Timing ------------------------------------------------------------

class TestServerTiming:
    def test_image_response_carries_stage_timings(self):
        async def fn(client, _origin, _app):
            res = await client.post("/resize?width=100", data=jpg())
            assert res.status == 200
            st = res.headers.get("Server-Timing", "")
            for name in ("fetch", "decode", "execute", "encode", "total"):
                assert re.search(rf"{name};dur=\d+(\.\d+)?", st), (name, st)

        run(ServerOptions(), fn)

    def test_device_path_stage_splits_reach_the_header(self):
        # PR 9/15 promised batch_form / dispatch_wait / drain stage
        # splits; the collector threads carry no trace contextvar, so
        # only the executor's direct per-item add_span stamps can get
        # them here (ISSUE 18 satellite)
        async def fn(client, _origin, _app):
            res = await client.post("/resize?width=100", data=jpg())
            assert res.status == 200
            st = res.headers.get("Server-Timing", "")
            for name in ("batch_form", "dispatch_wait", "drain"):
                assert re.search(rf"{name};dur=\d+(\.\d+)?", st), (name, st)

        run(ServerOptions(), fn)

    def test_tracing_disabled_still_sets_request_id(self):
        async def fn(client, _origin, _app):
            res = await client.post("/resize?width=100", data=jpg())
            assert res.status == 200
            assert "Server-Timing" not in res.headers
            assert re.fullmatch(r"[0-9a-f]{32}",
                                res.headers["X-Request-ID"])

        run(ServerOptions(trace_enabled=False), fn)


# --- wide events --------------------------------------------------------------

def _wide_events(stream: io.StringIO) -> list:
    return [json.loads(ln) for ln in stream.getvalue().splitlines()
            if ln.startswith("{")]


class TestWideEvents:
    def test_schema_and_5xx_correlation(self):
        stream = io.StringIO()

        async def fn(client, _origin, _app):
            res = await client.post("/resize?width=100", data=jpg())
            assert res.status == 200
            rid_ok = res.headers["X-Request-ID"]
            res = await client.post("/resize?width=100", data=b"notanimage")
            rid_bad = res.headers["X-Request-ID"]
            assert res.status >= 400

            events = _wide_events(stream)
            assert len(events) == 2
            ok = next(e for e in events if e["status"] == 200)
            for field in ("ts", "request_id", "trace_id", "span_id",
                          "method", "route", "path", "status", "remote",
                          "duration_ms", "bytes_in", "bytes_out", "op",
                          "plan", "cache", "placement", "spans"):
                assert field in ok, field
            assert ok["request_id"] == rid_ok
            assert ok["op"] == "resize"
            assert ok["cache"] == "off"
            assert ok["placement"] in ("device", "host")
            assert ok["bytes_in"] > 0 and ok["bytes_out"] > 0
            names = [s["name"] for s in ok["spans"]]
            assert "decode" in names and "encode" in names
            assert all(s["dur_ms"] >= 0 and "start_ms" in s
                       for s in ok["spans"])
            # the error event still carries the response's id (the 5xx
            # correlation contract; 4xx pins the same code path)
            bad = next(e for e in events if e["status"] >= 400)
            assert bad["request_id"] == rid_bad

        run(ServerOptions(wide_events=True), fn, log_stream=stream)

    def test_access_log_line_and_wide_event_share_id(self):
        stream = io.StringIO()

        async def fn(client, _origin, _app):
            res = await client.post("/resize?width=100", data=jpg())
            rid = res.headers["X-Request-ID"]
            text = stream.getvalue()
            log_line = next(ln for ln in text.splitlines()
                            if not ln.startswith("{"))
            assert log_line.rstrip().endswith(rid)
            assert _wide_events(stream)[0]["request_id"] == rid

        run(ServerOptions(wide_events=True), fn, log_stream=stream)

    def test_cache_and_coalesce_outcomes_recorded(self):
        stream = io.StringIO()

        async def fn(client, _origin, _app):
            for _ in range(2):
                res = await client.post("/resize?width=100", data=jpg())
                assert res.status == 200
            events = _wide_events(stream)
            assert events[0]["cache"] == "result_miss"
            assert events[1]["cache"] == "result_hit"

        run(ServerOptions(wide_events=True, cache_result_mb=16.0), fn,
            log_stream=stream)


# --- strict exposition-format parser -----------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? "
    r"(-?(?:\d+\.?\d*(?:[eE][+-]?\d+)?|\+?Inf|NaN))$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\["\\n])*)"')


def parse_exposition_strict(text: str):
    """Parse Prometheus text format 0.0.4 the way a scraper does; raise
    AssertionError on any violation: samples before their family's TYPE,
    duplicate TYPE, malformed labels, duplicate series."""
    types: dict = {}
    samples: list = []
    seen_series: set = set()
    assert text.endswith("\n")
    for ln in text.splitlines():
        assert ln.strip(), "blank line in exposition"
        if ln.startswith("# TYPE "):
            _, _, rest = ln.partition("# TYPE ")
            name, mtype = rest.split(" ", 1)
            assert mtype in ("counter", "gauge", "histogram", "summary",
                             "untyped"), ln
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = mtype
        elif ln.startswith("# HELP "):
            continue
        elif ln.startswith("#"):
            continue
        else:
            m = _SAMPLE_RE.match(ln)
            assert m, f"malformed sample line: {ln!r}"
            name, raw_labels, value = m.group(1), m.group(2), m.group(3)
            labels = {}
            if raw_labels:
                consumed = 0
                for lm in _LABEL_RE.finditer(raw_labels):
                    labels[lm.group(1)] = lm.group(2)
                    consumed += len(lm.group(0))
                stripped = raw_labels.replace(",", "")
                assert consumed == len(stripped), \
                    f"unparseable labels: {raw_labels!r}"
            family = name
            for suffix in ("_bucket", "_sum", "_count"):
                base = name[: -len(suffix)] if name.endswith(suffix) else None
                if base and types.get(base) == "histogram":
                    family = base
            assert family in types, f"sample before TYPE: {ln!r}"
            series = (name, tuple(sorted(labels.items())))
            assert series not in seen_series, f"duplicate series: {series}"
            seen_series.add(series)
            samples.append((name, labels, float(value.replace("Inf", "inf"))))
    return types, samples


def check_histograms(types, samples):
    """Every histogram family: buckets cumulative-monotone in le order,
    +Inf bucket == _count, _sum present."""
    for family, mtype in types.items():
        if mtype != "histogram":
            continue
        groups: dict = {}
        for name, labels, value in samples:
            if name == f"{family}_bucket":
                rest = tuple(sorted((k, v) for k, v in labels.items()
                                    if k != "le"))
                groups.setdefault(rest, []).append(
                    (float(labels["le"].replace("+Inf", "inf")), value))
        assert groups, f"histogram {family} emitted no buckets"
        counts = {tuple(sorted(labels.items())): value
                  for name, labels, value in samples
                  if name == f"{family}_count"}
        sums = {tuple(sorted(labels.items())): value
                for name, labels, value in samples
                if name == f"{family}_sum"}
        for rest, buckets in groups.items():
            buckets.sort()
            values = [v for _, v in buckets]
            assert all(a <= b for a, b in zip(values, values[1:])), \
                f"{family}{dict(rest)}: non-monotone buckets {values}"
            assert buckets[-1][0] == float("inf")
            assert rest in counts and counts[rest] == buckets[-1][1], \
                f"{family}{dict(rest)}: +Inf bucket != _count"
            assert rest in sums


class TestMetricsExposition:
    def test_strict_parse_and_histogram_consistency(self):
        async def fn(client, _origin, _app):
            for _ in range(3):
                res = await client.post("/resize?width=100", data=jpg())
                assert res.status == 200
            await client.get("/bogus")  # a 404 for the RED counters
            res = await client.get("/metrics")
            assert res.status == 200
            text = await res.text()
            types, samples = parse_exposition_strict(text)
            check_histograms(types, samples)
            names = {n for n, _, _ in samples}
            assert "imaginary_tpu_request_duration_seconds_bucket" in names
            assert "imaginary_tpu_stage_duration_seconds_bucket" in names
            assert "imaginary_tpu_requests_total" in names
            # RED counters: route x status class, bounded labels
            red = [(labels, v) for n, labels, v in samples
                   if n == "imaginary_tpu_requests_total"]
            assert any(labels.get("code") == "2xx" for labels, _ in red)
            assert any(labels.get("code") == "4xx"
                       and labels.get("route") == "unmatched"
                       for labels, _ in red)
            # stage histogram covers the pipeline stages
            stages = {labels["stage"] for n, labels, _ in samples
                      if n == "imaginary_tpu_stage_duration_seconds_bucket"}
            assert {"decode", "encode", "total"} <= stages
            # cache/executor counters are TYPEd as counters, gauges as gauges
            assert types["imaginary_tpu_executor_items"] == "counter"
            assert types["imaginary_tpu_executor_queue_depth"] == "gauge"

        run(ServerOptions(), fn)

    def test_label_values_escaped(self):
        from imaginary_tpu.web.metrics import render_metrics

        text = render_metrics({
            "backend": 'we"ird\\backend',
            "stageTimesMs": {
                'de"code': {"count": 3, "mean_ms": 1.0, "p50_ms": 1.0,
                            "p99_ms": 2.0},
            },
        })
        types, samples = parse_exposition_strict(text)
        backend = next(labels for n, labels, _ in samples
                       if n == "imaginary_tpu_backend_info")
        assert backend["backend"] == 'we\\"ird\\\\backend'

    def test_lane_families_render_strict(self):
        from imaginary_tpu.web.metrics import render_metrics

        text = render_metrics({
            "executor": {
                "items": 24,
                "batches": 6,
                "mesh_generation": 2,
                "lanes": [
                    {"lane": 0, "queued": 3, "inflight": 1, "owed": 4,
                     "ewma_ms": 2.5, "dispatches": 6, "active": True},
                    {"lane": 1, "queued": 0, "inflight": 0, "owed": 0,
                     "ewma_ms": 1.0, "dispatches": 9, "active": False},
                ],
                "wire_bytes_by_device": {
                    "h2d": {"0": 4096, "1": 2048},
                    "d2h": {"0": 1024},
                },
            },
        })
        types, samples = parse_exposition_strict(text)
        assert types["imaginary_tpu_lane_queued"] == "gauge"
        assert types["imaginary_tpu_lane_inflight"] == "gauge"
        assert types["imaginary_tpu_lane_dispatches_total"] == "counter"
        assert types["imaginary_tpu_executor_mesh_generation"] == "gauge"
        assert types["imaginary_tpu_wire_device_bytes_total"] == "counter"
        queued = {labels["lane"]: v for n, labels, v in samples
                  if n == "imaginary_tpu_lane_queued"}
        assert queued == {"0": 3.0, "1": 0.0}
        disp = {labels["lane"]: v for n, labels, v in samples
                if n == "imaginary_tpu_lane_dispatches_total"}
        assert disp == {"0": 6.0, "1": 9.0}
        wire = {(labels["direction"], labels["device"]): v
                for n, labels, v in samples
                if n == "imaginary_tpu_wire_device_bytes_total"}
        assert wire[("h2d", "0")] == 4096.0
        assert wire[("h2d", "1")] == 2048.0
        assert wire[("d2h", "0")] == 1024.0

    def test_lane_families_absent_when_policy_off(self):
        from imaginary_tpu.web.metrics import render_metrics

        # mesh_policy off: the executor block carries no lanes /
        # wire_bytes_by_device keys, and no lane family may leak out
        text = render_metrics({"executor": {"items": 24, "batches": 6}})
        parse_exposition_strict(text)
        assert "imaginary_tpu_lane_" not in text
        assert "imaginary_tpu_wire_device_bytes_total" not in text


# --- /debugz ------------------------------------------------------------------

class TestDebugz:
    def test_gated_off_by_default(self):
        async def fn(client, _origin, _app):
            res = await client.get("/debugz")
            assert res.status == 404
            res = await client.get("/debugz/profile?seconds=1")
            assert res.status == 404

        run(ServerOptions(), fn)

    def test_enabled_payload_shape(self):
        async def fn(client, _origin, _app):
            await client.post("/resize?width=100", data=jpg())
            res = await client.get("/debugz")
            assert res.status == 200
            body = await res.json()
            for key in ("pid", "threads", "tasks", "slowest_requests",
                        "executor", "executor_counters", "host_pool",
                        "cache"):
                assert key in body, key
            assert isinstance(body["tasks"], list)
            ex = body["executor"]
            for key in ("queue_depth", "inflight_groups", "breaker_open",
                        "owed_ms", "host_gate_free_permits"):
                assert key in ex, key
            assert body["host_pool"]["workers"] >= 1
            # slow-request exemplars carry the full span timeline
            slow = body["slowest_requests"]
            assert slow and "spans" in slow[0] and "request_id" in slow[0]

        obs_debugz.SLOW.clear()
        run(ServerOptions(enable_debug=True), fn)

    def test_api_key_guards_debugz_when_set(self):
        async def fn(client, _origin, _app):
            res = await client.get("/debugz")
            assert res.status == 401
            res = await client.get("/debugz", headers={"API-Key": "sekrit"})
            assert res.status == 200

        run(ServerOptions(enable_debug=True, api_key="sekrit"), fn)

    def test_profile_requires_destination(self, monkeypatch):
        monkeypatch.delenv("IMAGINARY_TPU_PROFILE_DIR", raising=False)

        async def fn(client, _origin, _app):
            res = await client.get("/debugz/profile?seconds=0.1")
            assert res.status == 400
            body = await res.json()
            assert "IMAGINARY_TPU_PROFILE_DIR" in body["error"]

        run(ServerOptions(enable_debug=True), fn)

    def test_profile_dir_query_param_overrides_env(self, monkeypatch,
                                                   tmp_path):
        # the no-restart path: a process booted WITHOUT the env var can
        # still name a destination per capture
        monkeypatch.delenv("IMAGINARY_TPU_PROFILE_DIR", raising=False)

        async def fn(client, _origin, _app):
            res = await client.get(
                "/debugz/profile", params={"seconds": "0.05",
                                           "dir": str(tmp_path)})
            assert res.status == 200
            body = await res.json()
            assert body["profile_dir"] == str(tmp_path)
            import os

            assert any(os.scandir(str(tmp_path)))

        run(ServerOptions(enable_debug=True), fn)

    def test_profile_one_shot_capture(self, monkeypatch, tmp_path):
        monkeypatch.setenv("IMAGINARY_TPU_PROFILE_DIR", str(tmp_path))

        async def fn(client, _origin, _app):
            res = await client.get("/debugz/profile?seconds=0.05")
            assert res.status == 200
            body = await res.json()
            assert body["profile_dir"] == str(tmp_path)
            # jax wrote a trace under the dir and the session is closed
            # (a second capture can start)
            import os

            assert any(os.scandir(str(tmp_path)))
            from imaginary_tpu.engine import timing

            assert not timing.profiler_active()

        run(ServerOptions(enable_debug=True), fn)

    def test_profile_bad_seconds_rejected(self, monkeypatch, tmp_path):
        monkeypatch.setenv("IMAGINARY_TPU_PROFILE_DIR", str(tmp_path))

        async def fn(client, _origin, _app):
            res = await client.get("/debugz/profile?seconds=nope")
            assert res.status == 400

        run(ServerOptions(enable_debug=True), fn)


# --- slow-request ring --------------------------------------------------------

class TestSlowRing:
    def test_slowest_ordering_and_bound(self):
        ring = obs_debugz.SlowRing(keep=4)
        for i, dur in enumerate([5.0, 50.0, 1.0, 20.0, 9.0]):
            ring.note({"request_id": str(i), "duration_ms": dur})
        top = ring.slowest(2)
        # the oldest entry (5.0) aged out of the keep=4 window
        assert [e["duration_ms"] for e in top] == [50.0, 20.0]
        assert len(ring.slowest(100)) == 4


# --- tail-sampled wide events (ISSUE 13) --------------------------------------

class TestClassify:
    """classify() precedence: the most actionable signal wins."""

    def test_interesting_tail_always_kept(self):
        cases = [
            ({"status": 503}, "shed"),
            ({"status": 504}, "deadline"),
            ({"status": 418}, "error"),
            ({"status": 200, "hedge": "won"}, "hedged"),
            ({"status": 200,
              "placement_attempts": ["device:0:error", "host_spill"]},
             "placement"),
            ({"status": 200,
              "placement_attempts": ["device:quarantined", "host_spill"]},
             "placement"),
            ({"status": 200, "fenced_publish": True}, "fenced"),
            ({"status": 200, "duration_ms": 1500.0}, "slow"),
        ]
        for event, want in cases:
            # sample=0: only the always-keep rules can save these events
            assert obs_events.classify(event, sample=0.0) == want, event

    def test_precedence_shed_beats_error_and_slow(self):
        ev = {"status": 503, "duration_ms": 9000.0}
        assert obs_events.classify(ev, sample=0.0) == "shed"
        ev = {"status": 200, "hedge": "lost", "duration_ms": 9000.0}
        assert obs_events.classify(ev, sample=0.0) == "hedged"

    def test_boring_event_sampling(self):
        boring = {"status": 200, "duration_ms": 3.0,
                  "placement_attempts": ["device:0"]}
        # default sample=1.0: everything kept (legacy parity)
        assert obs_events.classify(boring) == "random"
        assert obs_events.classify(boring, sample=0.0) == "unsampled"
        # injectable roll pins the probabilistic branch deterministically
        assert obs_events.classify(boring, sample=0.5,
                                   roll=lambda: 0.4) == "random"
        assert obs_events.classify(boring, sample=0.5,
                                   roll=lambda: 0.6) == "unsampled"

    def test_every_verdict_is_registered(self):
        # the ITPU010 contract from the python side
        for v in ("shed", "deadline", "error", "hedged", "placement",
                  "fenced", "slow", "random", "unsampled"):
            assert v in obs_events.SAMPLED_REASONS


class TestTailSampling:
    def test_sample_zero_keeps_only_the_interesting_tail(self):
        stream = io.StringIO()

        async def fn(client, _origin, _app):
            for _ in range(5):
                res = await client.post("/resize?width=100", data=jpg())
                assert res.status == 200
            res = await client.post("/resize?width=100", data=b"nope")
            assert res.status >= 400

            events = _wide_events(stream)
            # the five boring 200s were dropped; the error survived
            assert len(events) == 1
            assert events[0]["status"] >= 400
            assert events[0]["sampled_reason"] == "error"

        obs_debugz.SLOW.clear()
        run(ServerOptions(wide_events=True, wide_events_sample=0.0), fn,
            log_stream=stream)

    def test_default_sample_emits_everything_with_stamps(self):
        stream = io.StringIO()

        async def fn(client, _origin, _app):
            res = await client.post("/resize?width=100", data=jpg())
            assert res.status == 200
            events = _wide_events(stream)
            assert len(events) == 1
            ev = events[0]
            assert ev["sampled_reason"] == "random"
            # fleet attribution stamps (satellite a): a standalone
            # process is worker 0 at epoch 0
            assert ev["worker"] == 0
            assert ev["epoch"] == 0

        run(ServerOptions(wide_events=True), fn, log_stream=stream)

    def test_slow_ring_carries_verdict_even_for_unsampled(self):
        async def fn(client, _origin, _app):
            res = await client.post("/resize?width=100", data=jpg())
            assert res.status == 200

        obs_debugz.SLOW.clear()
        run(ServerOptions(wide_events=True, wide_events_sample=0.0), fn)
        entries = obs_debugz.SLOW.slowest(10)
        assert entries, "slow ring must record unsampled requests too"
        ev = entries[0]
        assert ev["sampled_reason"] == "unsampled"
        assert ev["worker"] == 0 and ev["epoch"] == 0


# --- exemplars (ISSUE 13) -----------------------------------------------------

class TestExemplars:
    def test_histogram_stores_and_renders_exemplar(self):
        reg = obs_hist.Registry()
        h = reg.histogram("ex_seconds", "help text", (0.1, 1.0))
        h.observe(0.05, exemplar=("req-1", "trace-1"))
        h.observe(0.5)
        plain = "\n".join(reg.render_lines()) + "\n"
        assert " # {" not in plain  # default render stays strict 0.0.4
        parse_exposition_strict(plain)
        rich = "\n".join(reg.render_lines(exemplars=True)) + "\n"
        assert 'trace_id="trace-1"' in rich
        assert 'request_id="req-1"' in rich
        # only the bucket that saw the exemplar carries one
        ex_lines = [ln for ln in rich.splitlines() if " # {" in ln]
        assert len(ex_lines) == 1 and 'le="0.1"' in ex_lines[0]

    def test_metrics_endpoint_exemplar_query(self):
        async def fn(client, _origin, _app):
            res = await client.post("/resize?width=100", data=jpg())
            rid = res.headers["X-Request-ID"]
            # plain scrape: byte-strict, no exemplar clause
            plain = await (await client.get("/metrics")).text()
            assert " # {" not in plain
            parse_exposition_strict(plain)
            # opted-in scrape: the request-duration bucket names the
            # exact request that landed in it
            rich = await (await client.get("/metrics?exemplars=1")).text()
            assert f'request_id="{rid}"' in rich
            # stripping the exemplar clause restores a strict body
            stripped = "\n".join(
                ln.split(" # {")[0] for ln in rich.splitlines()) + "\n"
            parse_exposition_strict(stripped)

        run(ServerOptions(), fn)


# --- SLO burn rates (ISSUE 13) ------------------------------------------------

class TestSloEngine:
    def test_load_config_inline_file_and_errors(self, tmp_path):
        from imaginary_tpu.obs import slo as slo_mod

        objectives = slo_mod.load_config(
            '{"/resize": {"latency_ms": 250, "latency_target": 0.99,'
            ' "availability": 0.999}}')
        assert objectives["/resize"].latency_ms == 250.0
        p = tmp_path / "slo.json"
        p.write_text('{"*": {"availability": 0.99}}')
        objectives = slo_mod.load_config(str(p))
        assert objectives["*"].availability == 0.99
        # defaults fill unspecified fields
        assert objectives["*"].latency_ms == 1000.0
        for bad in ("{nope", '{"*": 5}', '{"*": {"availability": 1.5}}',
                    '{"*": {"latency_ms": -1}}', str(tmp_path / "missing")):
            with pytest.raises(ValueError):
                slo_mod.load_config(bad)

    def test_burn_rate_math(self):
        from imaginary_tpu.obs import slo as slo_mod

        t = [1000.0]
        eng = slo_mod.SloEngine(
            slo_mod.load_config(
                '{"*": {"availability": 0.999, "latency_ms": 100,'
                ' "latency_target": 0.99}}'),
            clock=lambda: t[0])
        for _ in range(99):
            eng.observe("/resize", 200, 0.01)
        eng.observe("/resize", 500, 0.01)
        snap = eng.snapshot()
        r = snap["routes"]["/resize"]
        # 1 bad / 100 total against a 0.1% budget => burn 10x
        assert r["availability"]["burn_5m"] == pytest.approx(10.0)
        assert r["availability"]["bad_5m"] == 1
        assert r["availability"]["budget_remaining"] == 0.0
        # no over-latency requests: latency burn 0, budget intact
        assert r["latency"]["burn_5m"] == 0.0
        assert r["latency"]["budget_remaining"] == 1.0

    def test_sliding_window_forgets_old_badness(self):
        from imaginary_tpu.obs import slo as slo_mod

        t = [1000.0]
        eng = slo_mod.SloEngine(
            slo_mod.load_config('{"*": {"availability": 0.999}}'),
            clock=lambda: t[0])
        eng.observe("/x", 500, 0.01)  # ring snapshot at t=1000
        for _ in range(9):
            eng.observe("/x", 200, 0.01)
        t[0] += 6.0
        eng.observe("/x", 200, 0.01)  # second ring snapshot
        t[0] += 400.0  # the bad minute is now outside the 5m window...
        eng.observe("/x", 200, 0.01)
        snap = eng.snapshot()["routes"]["/x"]["availability"]
        assert snap["bad_5m"] == 0
        assert snap["burn_5m"] == 0.0
        # ...but still inside the 1h window
        assert snap["bad_1h"] == 1

    def test_unmatched_route_without_catchall_ignored(self):
        from imaginary_tpu.obs import slo as slo_mod

        eng = slo_mod.SloEngine(slo_mod.load_config(
            '{"/resize": {"availability": 0.999}}'))
        eng.observe("/other", 500, 0.01)
        assert eng.snapshot()["routes"] == {}

    def test_infra_routes_excluded_from_catchall(self):
        # the supervisor's liveness probes land ~0.5 rps of fast 200s
        # per worker on /health; a '*' objective must not let that
        # traffic dilute burn rates for real routes
        from imaginary_tpu.obs import slo as slo_mod

        eng = slo_mod.SloEngine(
            slo_mod.load_config('{"*": {"availability": 0.999}}'))
        for route in ("/health", "/metrics", "/debugz",
                      "/api/health", "/api/metrics"):
            eng.observe(route, 200, 0.001)
        eng.observe("/resize", 500, 0.01)
        routes = eng.snapshot()["routes"]
        assert set(routes) == {"/resize"}
        assert routes["/resize"]["availability"]["bad_5m"] == 1
        assert routes["/resize"]["availability"]["total_5m"] == 1

    def test_explicit_infra_objective_still_applies(self):
        from imaginary_tpu.obs import slo as slo_mod

        eng = slo_mod.SloEngine(slo_mod.load_config(
            '{"/health": {"availability": 0.999}}'))
        eng.observe("/health", 200, 0.001)
        assert eng.snapshot()["routes"]["/health"]["total"] == 1

    def test_from_options_parity_off(self):
        from imaginary_tpu.obs import slo as slo_mod

        assert slo_mod.from_options(ServerOptions()) is None
        assert slo_mod.from_options(
            ServerOptions(slo_config="  ")) is None


class TestSloSurfaces:
    SLO = '{"*": {"latency_ms": 500, "latency_target": 0.99, "availability": 0.999}}'

    def test_health_metrics_and_debugz_blocks(self):
        async def fn(client, _origin, _app):
            res = await client.post("/resize?width=100", data=jpg())
            assert res.status == 200
            health = await (await client.get("/health")).json()
            assert "slo" in health
            route = health["slo"]["routes"]["/resize"]
            assert route["total"] >= 1
            assert "burn_5m" in route["availability"]
            text = await (await client.get("/metrics")).text()
            types, samples = parse_exposition_strict(text)
            assert types["imaginary_tpu_slo_burn_rate"] == "gauge"
            burn = [(labels, v) for n, labels, v in samples
                    if n == "imaginary_tpu_slo_burn_rate"]
            assert {labels["slo"] for labels, _ in burn} \
                == {"availability", "latency"}
            assert {labels["window"] for labels, _ in burn} == {"5m", "1h"}
            assert any(n == "imaginary_tpu_slo_error_budget_remaining"
                       for n, _l, _v in samples)
            debug = await (await client.get("/debugz")).json()
            assert "slo" in debug

        run(ServerOptions(enable_debug=True, slo_config=self.SLO), fn)

    def test_parity_no_slo_block_without_config(self):
        async def fn(client, _origin, _app):
            await client.post("/resize?width=100", data=jpg())
            health = await (await client.get("/health")).json()
            assert "slo" not in health
            text = await (await client.get("/metrics")).text()
            assert "imaginary_tpu_slo_" not in text


# --- cost attribution & capacity plane (ISSUE 18) -----------------------------

class TestCostPlaneUnit:
    def test_parse_windows(self):
        from imaginary_tpu.obs import cost as cost_mod

        assert cost_mod.parse_windows("10s,1m,5m") == (
            ("10s", 10), ("1m", 60), ("5m", 300))
        for bad in ("", " , ", "10x", "10s,5s", "0s", "120m",
                    "1s,2s,3s,4s,5s,6s,7s"):
            with pytest.raises(ValueError):
                cost_mod.parse_windows(bad)

    def test_space_saving_fold_is_deterministic(self):
        from imaginary_tpu.obs.cost import SpaceSaving

        sk = SpaceSaving(2)
        assert sk.offer("a") is None
        assert sk.offer("a") is None
        assert sk.offer("b") is None
        # full table: the newcomer evicts the minimum entry — ties break
        # by (count, name), so replay order alone decides nothing
        assert sk.offer("c") == "b"
        assert sk.tracked("a") and sk.tracked("c") and not sk.tracked("b")
        # the newcomer inherited the victim's count floor
        assert dict(sk.top())["c"] == 2.0

    def test_booking_windows_and_topz_ranking(self):
        from imaginary_tpu.obs.cost import CostPlane

        t = [1000.0]
        plane = CostPlane(topk=4, windows="10s,1m", clock=lambda: t[0])
        for _ in range(3):
            plane.book("hog", "batch", "/process", "process",
                       device_ms=100.0, wire_bytes=5e6)
        for _ in range(2):
            plane.book("inter", "interactive", "/resize", "resize",
                       device_ms=1.0, host_ms=2.0, wire_bytes=1e4)
        snap = plane.snapshot()
        assert snap["booked"] == 5
        assert set(snap["windows"]) == {"10s", "1m"}
        assert snap["windows"]["10s"]["requests"] == 5
        assert snap["windows"]["10s"]["device_ms"] == pytest.approx(302.0)
        assert snap["tenants"]["hog"]["wire_bytes"] == 15_000_000
        topz = plane.topz()
        ranked = topz["windows"]["10s"]["by_chip_ms"]
        assert [r["tenant"] for r in ranked] == ["hog", "inter"]
        assert ranked[0]["chip_ms"] == pytest.approx(300.0)
        # host-ms ranking only lists tenants that actually burned host time
        assert [r["tenant"] for r in topz["windows"]["10s"]["by_host_ms"]] \
            == ["inter"]
        # 11 seconds later the 10s window has forgotten, the 1m one not
        t[0] += 11.0
        plane.book("late", "-", "/resize", "resize", device_ms=7.0)
        snap = plane.snapshot()
        assert snap["windows"]["10s"]["requests"] == 1
        assert snap["windows"]["10s"]["device_ms"] == pytest.approx(7.0)
        assert snap["windows"]["1m"]["requests"] == 6

    def test_topk_folds_into_other(self):
        from imaginary_tpu.obs.cost import OTHER, CostPlane

        t = [1000.0]
        plane = CostPlane(topk=2, windows="10s", clock=lambda: t[0])
        plane.book("a", "-", "/x", "x", device_ms=5.0)
        plane.book("a", "-", "/x", "x", device_ms=5.0)
        plane.book("b", "-", "/x", "x", device_ms=5.0)
        plane.book("c", "-", "/x", "x", device_ms=5.0)  # evicts b
        snap = plane.snapshot()
        assert snap["folds"] == 1
        assert set(snap["tenants"]) == {"a", "c", OTHER}
        # b's cumulative vector folded into `other`
        assert snap["tenants"][OTHER]["device_ms"] == pytest.approx(5.0)
        assert plane.normalize("tenant", "b") == OTHER
        assert plane.normalize("tenant", "a") == "a"
        # route/qos_class kinds pass through; unknown kinds raise
        assert plane.normalize("route", "/whatever") == "/whatever"
        with pytest.raises(ValueError):
            plane.normalize("flavor", "x")

    def test_seeded_tenants_never_report_other(self):
        from imaginary_tpu.obs.cost import CostPlane

        plane = CostPlane(topk=4, windows="10s")
        plane.seed_tenants(("gold", "bronze"))
        assert plane.normalize("tenant", "gold") == "gold"
        assert plane.normalize("tenant", "stranger") == "other"

    def test_should_book_skips_infra_routes(self):
        from imaginary_tpu.obs.cost import CostPlane

        plane = CostPlane()
        for route in ("/", "/health", "/metrics", "/topz", "/fleetz",
                      "/api/health", "/debugz"):
            assert not plane.should_book(route), route
        for route in ("/resize", "/process", "/api/crop"):
            assert plane.should_book(route), route

    def test_advisor_unknown_without_traffic(self):
        from imaginary_tpu.obs.cost import CostPlane

        plane = CostPlane(windows="10s")
        verdict = plane.advise()
        assert verdict["verdict"] == "unknown"

    def test_advisor_verdict_argmin(self):
        from imaginary_tpu.obs.cost import SERVING_BATCH, CostPlane

        class _Ex:
            _drain_floor_ms = 80.0
            _device_ms_per_mb = 2.0

        t = [1000.0]
        plane = CostPlane(topk=4, windows="10s", clock=lambda: t[0])
        plane.bind(executor=_Ex(), host_view=lambda: (4, 0))
        plane.book("t", "-", "/process", "process",
                   device_ms=20.0, host_ms=1.0, wire_bytes=10e6)
        out = plane.advise()
        # link: 80/16 + 10*2 = 25 ms/req; chip: 20 ms/req; host: 1/4
        assert out["serving_batch"] == SERVING_BATCH
        assert out["link_rate"] == pytest.approx(1000.0 / 25.0)
        assert out["chip_rate"] == pytest.approx(50.0)
        assert out["verdict"] == "link"
        assert out["e2e_rate"] == pytest.approx(40.0)

    def test_from_options_parity_and_install(self):
        from imaginary_tpu.obs import cost as cost_mod

        assert cost_mod.from_options(ServerOptions()) is None
        assert cost_mod.active() is None
        plane = cost_mod.from_options(
            ServerOptions(cost_attribution=True, cost_topk=7))
        try:
            assert plane is not None and plane.topk == 7
            assert cost_mod.active() is plane
            # armed: normalize_label delegates to the plane
            assert cost_mod.normalize_label("tenant", "ghost") == "other"
        finally:
            cost_mod.install(None)
        # disarmed: identity passthrough, but kinds still validated
        assert cost_mod.normalize_label("tenant", "ghost") == "ghost"
        with pytest.raises(ValueError):
            cost_mod.normalize_label("flavor", "x")


class TestCostSurfaces:
    def test_armed_health_metrics_topz_debugz(self):
        async def fn(client, _origin, _app):
            for _ in range(2):
                res = await client.post("/resize?width=100", data=jpg())
                assert res.status == 200
            health = await (await client.get("/health")).json()
            cap = health["capacity"]
            assert cap["booked"] >= 2
            assert set(cap["windows"]) == {"10s", "1m", "5m"}
            assert cap["tenants"]["default"]["requests"] >= 2
            assert "verdict" in cap["bound_by"]
            assert "wait_cum_ms" in cap["utilization"]
            # scrape twice: utilization busy fractions are deltas
            # between snapshots, so the second scrape carries them
            await client.get("/metrics")
            text = await (await client.get("/metrics")).text()
            types, samples = parse_exposition_strict(text)
            names = {n for n, _, _ in samples}
            for field in ("device_ms", "host_ms", "wire_bytes",
                          "copied_bytes", "cache_bytes", "requests"):
                fam = f"imaginary_tpu_cost_{field}_total"
                assert fam in names, fam
                assert types[fam] == "counter"
            assert "imaginary_tpu_cost_folds_total" in names
            assert "imaginary_tpu_cost_booked_total" in names
            assert types["imaginary_tpu_utilization_wait_ms_total"] \
                == "counter"
            assert {labels["kind"] for n, labels, _ in samples
                    if n == "imaginary_tpu_utilization_wait_ms_total"} \
                == {"batch_form", "dispatch_wait", "link_stall", "drain"}
            assert types["imaginary_tpu_utilization_chip_busy"] == "gauge"
            assert "imaginary_tpu_utilization_host_pool" in names
            # every cost family is tenant-labeled with the booked tenant
            reqs = [(labels, v) for n, labels, v in samples
                    if n == "imaginary_tpu_cost_requests_total"]
            assert any(labels.get("tenant") == "default" and v >= 2
                       for labels, v in reqs)
            topz = await client.get("/topz")
            assert topz.status == 200
            body = await topz.json()
            assert body["k"] == 20
            assert body["windows"]["5m"]["totals"]["requests"] >= 2
            ranked = body["windows"]["5m"]["by_chip_ms"]
            assert ranked and ranked[0]["tenant"] == "default"
            debug = await (await client.get("/debugz")).json()
            assert "capacity" in debug

        run(ServerOptions(cost_attribution=True, enable_debug=True), fn)

    def test_off_by_default_parity(self):
        collected = {}

        async def armed(client, _origin, _app):
            res = await client.post("/resize?width=100", data=jpg())
            assert res.status == 200
            collected["armed"] = await res.read()

        async def off(client, _origin, _app):
            res = await client.post("/resize?width=100", data=jpg())
            assert res.status == 200
            collected["off"] = await res.read()
            health = await (await client.get("/health")).json()
            assert "capacity" not in health
            text = await (await client.get("/metrics")).text()
            assert "imaginary_tpu_cost_" not in text
            assert "imaginary_tpu_utilization_" not in text
            topz = await client.get("/topz")
            assert topz.status == 404
            debug = await (await client.get("/debugz")).json()
            assert "capacity" not in debug

        run(ServerOptions(cost_attribution=True), armed)
        run(ServerOptions(enable_debug=True), off)
        # the image path is byte-identical with the plane disarmed
        assert collected["armed"] == collected["off"]

    def test_capacity_render_is_strict_and_normalized(self):
        # synthetic capacity block straight through render_metrics: the
        # exposition stays strict and tenant label values are escaped
        from imaginary_tpu.web.metrics import render_metrics

        text = render_metrics({
            "capacity": {
                "topk": 2, "folds": 3, "booked": 9,
                "windows": {"10s": {"device_ms": 1.0, "requests": 2}},
                "tenants": {
                    'we"ird': {"device_ms": 1.5, "host_ms": 0.0,
                               "wire_bytes": 10, "copied_bytes": 4,
                               "cache_bytes": 0, "requests": 2},
                },
                "utilization": {
                    "age_s": 1.0,
                    "wait_cum_ms": {"batch_form": 1.0, "drain": 2.0},
                    "lanes": {"0": 0.5, "all": 0.1},
                    "chip_busy": 0.3, "host_pool": 0.25, "link": 0.1,
                },
                "bound_by": {"verdict": "chip"},
            },
            "eventLoop": {"lagMsLast": 12.0, "lagMsMax": 80.0,
                          "samples": 5},
        })
        types, samples = parse_exposition_strict(text)
        assert types["imaginary_tpu_cost_device_ms_total"] == "counter"
        tenants = {labels["tenant"] for n, labels, _ in samples
                   if n == "imaginary_tpu_cost_device_ms_total"}
        # the strict parser keeps label values raw: the quote arrived
        # backslash-escaped on the wire, which is the point
        assert tenants == {'we\\"ird'}
        lane = {labels["lane"]: v for n, labels, v in samples
                if n == "imaginary_tpu_utilization_lane_busy"}
        assert lane == {"0": 0.5, "all": 0.1}
        gauges = {n: v for n, _l, v in samples}
        assert gauges["imaginary_tpu_utilization_chip_busy"] == 0.3
        assert gauges["imaginary_tpu_event_loop_lag_last_seconds"] \
            == pytest.approx(0.012)
        assert gauges["imaginary_tpu_event_loop_lag_max_seconds"] \
            == pytest.approx(0.080)


class TestLoopLag:
    def test_probe_samples_and_snapshot(self):
        from imaginary_tpu.obs import looplag

        async def probe():
            task = looplag.start(0.01)
            await asyncio.sleep(0.08)
            looplag.stop(task)

        asyncio.run(probe())
        snap = looplag.snapshot()
        assert snap is not None
        assert snap["samples"] >= 1
        assert snap["lagMsMax"] >= snap["lagMsLast"] >= 0.0
        assert looplag.last_ms() == pytest.approx(
            snap["lagMsLast"], abs=1e-3)

    def test_health_carries_event_loop_block(self):
        async def fn(client, _origin, _app):
            # the probe runs at 4 Hz from app startup; wait one period
            await asyncio.sleep(0.3)
            health = await (await client.get("/health")).json()
            assert health["eventLoop"]["samples"] >= 1

        run(ServerOptions(), fn)


class TestFleetCapacityMerge:
    def test_fleetz_merges_capacity_across_workers(self):
        from imaginary_tpu.obs.aggregate import build_fleetz

        def health(verdict, device_ms, folds=0):
            return {
                "worker": 0, "epoch": 1,
                "capacity": {
                    "folds": folds,
                    "windows": {"10s": {"device_ms": device_ms,
                                        "requests": 2}},
                    "bound_by": {"verdict": verdict},
                },
            }

        view = {0: {"pid": 10, "alive": True}, 1: {"pid": 11, "alive": True}}
        out = build_fleetz(
            view,
            {0: health("chip", 10.0, folds=1),
             1: health("link", 5.0, folds=2)},
            missed=set(), now=123.0)
        cap = out["capacity"]
        assert cap["workers"] == [0, 1]
        assert cap["folds"] == 3
        assert cap["windows"]["10s"]["device_ms"] == pytest.approx(15.0)
        assert cap["windows"]["10s"]["requests"] == 4
        assert cap["bound_by"] == {"0": "chip", "1": "link"}

    def test_fleetz_parity_without_capacity(self):
        from imaginary_tpu.obs.aggregate import build_fleetz

        out = build_fleetz({0: {"pid": 10}}, {0: {"worker": 0}},
                           missed=set(), now=123.0)
        assert "capacity" not in out
