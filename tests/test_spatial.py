"""Spatially-sharded blur: shard_map halo exchange must match the
single-device normalized-conv blur exactly (same math, different layout).

Guards, not collection errors: the module imports jax lazily-enough to
skip cleanly when the multi-device topology (8 devices, from conftest's
XLA_FLAGS or real hardware) is absent — a bare `imaginary_tpu.parallel`
import failure must read as SKIPPED topology, not a broken suite."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from imaginary_tpu.ops.stages import BlurSpec

spatial_mod = pytest.importorskip(
    "imaginary_tpu.parallel.spatial",
    reason="spatial sharding unavailable (no shard_map on this jax)")
sharded_blur = spatial_mod.sharded_blur

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs 8 devices (XLA_FLAGS=--xla_force_host_platform_device_count=8)")


def _mesh(batch, spatial):
    devs = np.array(jax.devices()[: batch * spatial]).reshape(batch, spatial)
    return Mesh(devs, ("batch", "spatial"))


@pytest.mark.parametrize("spatial", [2, 4])
def test_sharded_blur_matches_local(spatial):
    mesh = _mesh(8 // spatial, spatial)
    rng = np.random.default_rng(0)
    b = 8 // spatial * 2
    x = rng.integers(0, 256, (b, 64, 128, 3)).astype(np.float32)
    h = np.full((b,), 60, np.int32)   # valid region smaller than bucket
    w = np.full((b,), 120, np.int32)
    sigma = np.full((b,), 3.0, np.float32)

    out_sh = np.asarray(sharded_blur(jnp.asarray(x), jnp.asarray(h), jnp.asarray(w),
                                     jnp.asarray(sigma), radius=8, mesh=mesh))

    ref, _, _ = BlurSpec(radius=8).apply(jnp.asarray(x), jnp.asarray(h), jnp.asarray(w),
                                         {"sigma": jnp.asarray(sigma)})
    ref = np.asarray(ref)
    np.testing.assert_allclose(out_sh, ref, atol=1e-2)


def test_halo_radius_guard():
    mesh = _mesh(2, 4)
    x = jnp.zeros((2, 16, 64, 3))
    with pytest.raises(ValueError, match="halo radius"):
        sharded_blur(x, jnp.array([16, 16]), jnp.array([64, 64]),
                     jnp.array([1.0, 1.0]), radius=16, mesh=mesh)
