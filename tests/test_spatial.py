"""Spatially-sharded blur: shard_map halo exchange must match the
single-device normalized-conv blur exactly (same math, different layout)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from imaginary_tpu.ops.stages import BlurSpec
from imaginary_tpu.parallel.spatial import sharded_blur


def _mesh(batch, spatial):
    devs = np.array(jax.devices()[: batch * spatial]).reshape(batch, spatial)
    return Mesh(devs, ("batch", "spatial"))


@pytest.mark.parametrize("spatial", [2, 4])
def test_sharded_blur_matches_local(spatial):
    mesh = _mesh(8 // spatial, spatial)
    rng = np.random.default_rng(0)
    b = 8 // spatial * 2
    x = rng.integers(0, 256, (b, 64, 128, 3)).astype(np.float32)
    h = np.full((b,), 60, np.int32)   # valid region smaller than bucket
    w = np.full((b,), 120, np.int32)
    sigma = np.full((b,), 3.0, np.float32)

    out_sh = np.asarray(sharded_blur(jnp.asarray(x), jnp.asarray(h), jnp.asarray(w),
                                     jnp.asarray(sigma), radius=8, mesh=mesh))

    ref, _, _ = BlurSpec(radius=8).apply(jnp.asarray(x), jnp.asarray(h), jnp.asarray(w),
                                         {"sigma": jnp.asarray(sigma)})
    ref = np.asarray(ref)
    np.testing.assert_allclose(out_sh, ref, atol=1e-2)


def test_halo_radius_guard():
    mesh = _mesh(2, 4)
    x = jnp.zeros((2, 16, 64, 3))
    with pytest.raises(ValueError, match="halo radius"):
        sharded_blur(x, jnp.array([16, 16]), jnp.array([64, 64]),
                     jnp.array([1.0, 1.0]), radius=16, mesh=mesh)
