"""Content-addressed cache subsystem (imaginary_tpu/cache.py).

Covers the acceptance list from the cache PR: LRU hit/miss/eviction under
a byte budget, ETag/If-None-Match -> 304, singleflight fan-out (one
pipeline run for N concurrent identical requests, error propagated to all
waiters, no _inflight leak on waiter cancellation), cache-off parity
(all tiers disabled => byte-identical responses to uncached behavior),
the decoded-frame tier, the TTL'd remote-source tier, and the
oversize-remote-body rejection that replaced LimitReader truncation.
"""

import asyncio
import io
import json

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from imaginary_tpu import cache as cache_mod
from imaginary_tpu.web.config import ServerOptions
from tests.conftest import fixture_bytes


@pytest.fixture(scope="module", autouse=True)
def _fixtures(testdata):
    return testdata


def run(options, fn, origin_handler=None):
    """Run `fn(client, origin_url, app)` against a fresh app instance
    (test_server.py's harness, plus the app handle so tests can reach
    service.caches counters)."""

    async def runner():
        from imaginary_tpu.web.app import create_app

        origin_url = None
        origin = None
        if origin_handler is not None:
            oapp = web.Application()
            oapp.router.add_route("*", "/{tail:.*}", origin_handler)
            origin = TestServer(oapp)
            await origin.start_server()
            origin_url = f"http://127.0.0.1:{origin.port}"

        app = create_app(options, log_stream=io.StringIO())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await fn(client, origin_url, app)
        finally:
            await client.close()
            if origin is not None:
                await origin.close()

    asyncio.run(runner())


def jpg() -> bytes:
    return fixture_bytes("imaginary.jpg")


# --- ByteBudgetLRU unit behavior ---------------------------------------------

class TestByteBudgetLRU:
    def test_hit_miss_and_lru_order(self):
        lru = cache_mod.ByteBudgetLRU(100)
        assert lru.get("a") is None
        lru.put("a", b"xxxx", 40)
        lru.put("b", b"yyyy", 40)
        assert lru.get("a") == b"xxxx"  # refreshes a's recency
        lru.put("c", b"zzzz", 40)  # budget 100: evicts b (LRU), not a
        assert lru.get("b") is None
        assert lru.get("a") == b"xxxx"
        assert lru.get("c") == b"zzzz"

    def test_eviction_respects_byte_budget_and_counts(self):
        evicted = []
        lru = cache_mod.ByteBudgetLRU(100, on_evict=evicted.append)
        for i in range(5):
            lru.put(i, i, 30)  # 5 x 30 > 100: two must go
        assert lru.bytes_used <= 100
        assert sum(evicted) == 2
        assert len(lru) == 3

    def test_oversize_entry_refused(self):
        lru = cache_mod.ByteBudgetLRU(100)
        lru.put("big", b"x", 101)
        assert lru.get("big") is None
        assert lru.bytes_used == 0

    def test_replace_same_key_adjusts_bytes(self):
        lru = cache_mod.ByteBudgetLRU(100)
        lru.put("a", 1, 60)
        lru.put("a", 2, 30)
        assert lru.bytes_used == 30
        assert lru.get("a") == 2

    def test_zero_budget_disabled(self):
        lru = cache_mod.ByteBudgetLRU(0)
        assert not lru.enabled
        lru.put("a", 1, 1)
        assert lru.get("a") is None

    def test_ttl_expiry(self, monkeypatch):
        import time as time_mod

        now = [1000.0]
        monkeypatch.setattr(cache_mod.time, "monotonic", lambda: now[0])
        lru = cache_mod.ByteBudgetLRU(100, ttl_s=5.0)
        lru.put("a", b"v", 10)
        assert lru.get("a") == b"v"
        now[0] += 6.0
        assert lru.get("a") is None
        assert len(lru) == 0
        del time_mod  # silence linters; monkeypatch target is cache_mod.time


# --- key derivation / ETag ----------------------------------------------------

class TestKeys:
    def test_key_sensitive_to_source_op_and_options(self):
        from imaginary_tpu.options import ImageOptions

        d1 = cache_mod.source_digest(b"abc")
        d2 = cache_mod.source_digest(b"abd")
        o1 = ImageOptions(width=100)
        o2 = ImageOptions(width=101)
        k = cache_mod.request_key
        assert k(d1, "resize", o1) == k(d1, "resize", ImageOptions(width=100))
        assert k(d1, "resize", o1) != k(d2, "resize", o1)
        assert k(d1, "resize", o1) != k(d1, "crop", o1)
        assert k(d1, "resize", o1) != k(d1, "resize", o2)

    def test_key_covers_pipeline_operations(self):
        from imaginary_tpu.options import ImageOptions, PipelineOperation

        d = cache_mod.source_digest(b"abc")
        o1 = ImageOptions(operations=[
            PipelineOperation(name="crop", params={"width": 100})])
        o2 = ImageOptions(operations=[
            PipelineOperation(name="crop", params={"width": 200})])
        assert (cache_mod.request_key(d, "pipeline", o1)
                != cache_mod.request_key(d, "pipeline", o2))

    def test_strong_etag_stable_and_quoted(self):
        from imaginary_tpu.options import ImageOptions

        d = cache_mod.source_digest(b"abc")
        k = cache_mod.request_key(d, "resize", ImageOptions(width=9))
        e1 = cache_mod.strong_etag(k)
        e2 = cache_mod.strong_etag(
            cache_mod.request_key(d, "resize", ImageOptions(width=9)))
        assert e1 == e2
        assert e1.startswith('"') and e1.endswith('"')

    def test_etag_match_list_and_star(self):
        m = cache_mod.etag_matches
        assert m('"abc"', '"abc"')
        assert m('"x", "abc"', '"abc"')
        assert m("*", '"abc"')
        assert not m('W/"abc"', '"abc"')
        assert not m("", '"abc"')


# --- singleflight -------------------------------------------------------------

class TestSingleflight:
    def test_fanout_and_leader_counts(self):
        async def go():
            sf = cache_mod.Singleflight()
            runs = []

            async def thunk():
                runs.append(1)
                await asyncio.sleep(0.05)
                return "v"

            got = await asyncio.gather(*[sf.run("k", thunk) for _ in range(8)])
            assert got == ["v"] * 8
            assert len(runs) == 1
            assert sf.stats.flight_executed == 1
            assert sf.stats.flight_coalesced == 7
            assert sf.inflight() == 0

        asyncio.run(go())

    def test_error_propagates_to_all_waiters(self):
        async def go():
            sf = cache_mod.Singleflight()

            async def thunk():
                await asyncio.sleep(0.02)
                raise ValueError("boom")

            results = await asyncio.gather(
                *[sf.run("k", thunk) for _ in range(4)], return_exceptions=True
            )
            assert all(isinstance(r, ValueError) for r in results)
            assert sf.inflight() == 0

        asyncio.run(go())

    def test_waiter_cancellation_does_not_cancel_group(self):
        async def go():
            sf = cache_mod.Singleflight()
            done = asyncio.Event()

            async def thunk():
                await asyncio.sleep(0.05)
                done.set()
                return "v"

            leader = asyncio.ensure_future(sf.run("k", thunk))
            await asyncio.sleep(0.01)
            waiter = asyncio.ensure_future(sf.run("k", thunk))
            await asyncio.sleep(0.01)
            waiter.cancel()
            # the cancelled waiter detaches; the group still completes and
            # the leader still gets the value
            assert await leader == "v"
            assert done.is_set()
            assert sf.inflight() == 0

        asyncio.run(go())

    def test_leader_request_cancellation_keeps_group_running(self):
        async def go():
            sf = cache_mod.Singleflight()
            done = asyncio.Event()

            async def thunk():
                await asyncio.sleep(0.05)
                done.set()
                return "v"

            leader = asyncio.ensure_future(sf.run("k", thunk))
            await asyncio.sleep(0.01)
            follower = asyncio.ensure_future(sf.run("k", thunk))
            await asyncio.sleep(0.0)
            leader.cancel()
            # the group task is independent of the leader's await: the
            # follower still gets the result
            assert await follower == "v"
            assert done.is_set()
            assert sf.inflight() == 0

        asyncio.run(go())


# --- end-to-end: result cache + ETag over HTTP --------------------------------

def _caches(app):
    return app["service"].caches


class TestResultCacheHTTP:
    def test_hit_serves_identical_bytes_without_second_run(self):
        async def fn(client, _origin, app):
            res1 = await client.post("/resize?width=120&height=80",
                                     data=jpg())
            assert res1.status == 200
            body1 = await res1.read()
            etag = res1.headers.get("ETag")
            assert etag  # result tier on => strong ETag on the response
            res2 = await client.post("/resize?width=120&height=80",
                                     data=jpg())
            body2 = await res2.read()
            assert body2 == body1
            assert res2.headers.get("ETag") == etag
            st = _caches(app).stats
            assert st.result_hits == 1
            assert st.result_misses == 1

        run(ServerOptions(cache_result_mb=8.0), fn)

    def test_distinct_params_distinct_entries(self):
        async def fn(client, _origin, app):
            r1 = await client.post("/resize?width=120&height=80", data=jpg())
            r2 = await client.post("/resize?width=121&height=80", data=jpg())
            assert r1.headers["ETag"] != r2.headers["ETag"]
            assert _caches(app).stats.result_hits == 0
            assert _caches(app).stats.result_misses == 2

        run(ServerOptions(cache_result_mb=8.0), fn)

    def test_if_none_match_304_before_pipeline(self, monkeypatch):
        async def fn(client, _origin, app):
            res1 = await client.get("/resize?width=120&height=80&file=imaginary.jpg")
            assert res1.status == 200
            etag = res1.headers["ETag"]

            # a 304 must answer BEFORE the pipeline runs: poison the
            # process path and prove it is never reached
            from imaginary_tpu.web.handlers import ImageService

            def boom(*a, **k):
                raise AssertionError("pipeline ran on a conditional GET hit")

            monkeypatch.setattr(ImageService, "_process_sync", boom)
            res2 = await client.get(
                "/resize?width=120&height=80&file=imaginary.jpg",
                headers={"If-None-Match": etag},
            )
            assert res2.status == 304
            assert res2.headers["ETag"] == etag
            assert await res2.read() == b""
            assert _caches(app).stats.etag_304 == 1

            # non-matching validator: full 200 (from cache)
            res3 = await client.get(
                "/resize?width=120&height=80&file=imaginary.jpg",
                headers={"If-None-Match": '"deadbeef"'},
            )
            assert res3.status == 200

        import os

        from tests.conftest import FIXTURES

        assert os.path.isdir(FIXTURES)
        run(ServerOptions(cache_result_mb=8.0, mount=FIXTURES), fn)

    def test_eviction_under_byte_budget_http(self):
        # Deterministic byte accounting, two passes. The old version
        # hardcoded a 0.006 MB budget against "~3-6 KB" bodies, which
        # flaked under host load: the cost model flips placement (device
        # vs host SIMD) with load, the two backends' pixels are
        # PSNR-equivalent but not bit-identical, and the encoded sizes
        # moved across the magic budget. force_host pins placement (so
        # bodies are the same bytes every run), pass 1 MEASURES them,
        # and pass 2 sets the budget from the measurement: large enough
        # for any single body, too small for any two.
        sizes: dict = {}

        async def measure(client, _origin, app):
            for w in (100, 110, 120):
                res = await client.post(f"/resize?width={w}&height=70",
                                        data=jpg())
                assert res.status == 200
                sizes[w] = len(await res.read())

        run(ServerOptions(force_host=True), measure)
        ordered = sorted(sizes.values())
        budget_bytes = ordered[0] + ordered[1] - 1  # any one fits, no two do
        assert budget_bytes >= max(ordered)

        async def fn(client, _origin, app):
            # at most one entry ever resident: every request must miss
            # and evict its predecessor
            for w in (100, 110, 120, 100, 110, 120):
                res = await client.post(f"/resize?width={w}&height=70",
                                        data=jpg())
                assert res.status == 200
            st = _caches(app).stats
            assert st.result_evictions > 0
            assert st.result_hits == 0
            assert st.result_misses == 6

        run(ServerOptions(cache_result_mb=budget_bytes / 1e6,
                          force_host=True), fn)

    def test_accept_negotiation_keys_separately(self):
        async def fn(client, _origin, app):
            r1 = await client.post("/resize?width=100&type=auto", data=jpg(),
                                   headers={"Accept": "image/png"})
            r2 = await client.post("/resize?width=100&type=auto", data=jpg(),
                                   headers={"Accept": "image/jpeg"})
            assert r1.headers["Content-Type"] == "image/png"
            assert r2.headers["Content-Type"] == "image/jpeg"
            # negotiated outputs must not share an entry or an ETag
            assert r1.headers["ETag"] != r2.headers["ETag"]
            assert _caches(app).stats.result_hits == 0

        run(ServerOptions(cache_result_mb=8.0), fn)


class TestCoalescingHTTP:
    def test_n_identical_concurrent_requests_one_pipeline_run(self):
        async def fn(client, _origin, app):
            from imaginary_tpu.web import handlers as handlers_mod

            runs = []
            inner = handlers_mod.ImageService._process_sync_inner

            def counting(self, *a, **k):
                runs.append(1)
                return inner(self, *a, **k)

            handlers_mod.ImageService._process_sync_inner = counting
            try:
                body = jpg()
                res = await asyncio.gather(*[
                    client.post("/resize?width=140&height=90", data=body)
                    for _ in range(12)
                ])
                assert all(r.status == 200 for r in res)
                bodies = [await r.read() for r in res]
                assert len(set(bodies)) == 1  # one result fanned out
            finally:
                handlers_mod.ImageService._process_sync_inner = inner
            st = _caches(app).stats
            assert len(runs) == 1  # the pipeline executed exactly once
            assert st.flight_executed == 1
            assert st.flight_coalesced == 11
            # the group counted as ONE unit of queue pressure and released it
            assert app["service"]._inflight == 0

        run(ServerOptions(cache_coalesce=True), fn)

    def test_error_fans_out_to_every_waiter_without_inflight_leak(self):
        async def fn(client, _origin, app):
            # /extract without area params raises in the pool thread
            body = jpg()
            res = await asyncio.gather(*[
                client.post("/extract?top=10", data=body) for _ in range(6)
            ])
            assert all(r.status == 400 for r in res)
            payloads = [json.loads(await r.read()) for r in res]
            assert len({p["message"] for p in payloads}) == 1
            assert app["service"]._inflight == 0

        run(ServerOptions(cache_coalesce=True), fn)


class TestFrameCacheHTTP:
    def test_second_request_on_same_source_skips_decode(self):
        async def fn(client, _origin, app):
            # same geometry (=> same shrink-on-load, same frame key) but
            # different encode quality: distinct results, shared frame
            r1 = await client.post("/resize?width=130&height=85&quality=80",
                                   data=jpg())
            r2 = await client.post("/resize?width=130&height=85&quality=55",
                                   data=jpg())
            assert r1.status == 200 and r2.status == 200
            st = _caches(app).stats
            assert st.frame_hits >= 1

        run(ServerOptions(cache_frame_mb=64.0), fn)


class TestSourceCacheHTTP:
    def test_hot_url_fetched_once_per_ttl(self):
        fetches = []

        async def origin(request):
            fetches.append(request.method)
            return web.Response(body=jpg(), content_type="image/jpeg")

        async def fn(client, origin_url, app):
            url = origin_url + "/img.jpg"
            for _ in range(3):
                res = await client.get(f"/resize?width=100&url={url}")
                assert res.status == 200
            st = _caches(app).stats
            assert fetches.count("GET") == 1
            assert st.source_hits == 2
            assert st.source_misses == 1

        run(ServerOptions(enable_url_source=True, cache_source_ttl=60.0),
            fn, origin_handler=origin)

    def test_source_cache_off_fetches_every_time(self):
        fetches = []

        async def origin(request):
            fetches.append(request.method)
            return web.Response(body=jpg(), content_type="image/jpeg")

        async def fn(client, origin_url, app):
            url = origin_url + "/img.jpg"
            for _ in range(2):
                res = await client.get(f"/resize?width=100&url={url}")
                assert res.status == 200
            assert fetches.count("GET") == 2

        run(ServerOptions(enable_url_source=True), fn, origin_handler=origin)


class TestOversizeRemoteBody:
    def test_oversize_streamed_body_rejected_not_truncated(self):
        async def origin(request):
            # chunked response (no Content-Length): the HEAD pre-check
            # cannot catch it, so the streaming guard must
            resp = web.StreamResponse()
            resp.enable_chunked_encoding()
            await resp.prepare(request)
            if request.method != "HEAD":
                await resp.write(b"\xff" * 5000)
            await resp.write_eof()
            return resp

        async def fn(client, origin_url, app):
            res = await client.get(f"/resize?width=100&url={origin_url}/big.jpg")
            # entity-too-large, NOT a 400 corrupt-decode from truncation
            assert res.status == 413
            payload = json.loads(await res.read())
            assert "large" in payload["message"].lower()

        run(ServerOptions(enable_url_source=True, max_allowed_size=1000),
            fn, origin_handler=origin)


class TestCacheOffParity:
    def test_disabled_tiers_are_byte_identical_to_uncached(self):
        bodies = {}

        async def capture(label, client):
            res = await client.post("/resize?width=150&height=100", data=jpg())
            assert res.status == 200
            assert "ETag" not in res.headers or label == "on"
            bodies[label] = await res.read()
            return res

        async def fn_off(client, _origin, app):
            res = await capture("off", client)
            assert "ETag" not in res.headers
            # default options: every tier reads disabled
            c = _caches(app)
            assert not c.result.enabled and not c.frames.enabled
            assert not c.source.enabled and not c.coalesce

        async def fn_off2(client, _origin, app):
            await capture("off2", client)

        async def fn_on(client, _origin, app):
            await capture("on", client)

        run(ServerOptions(), fn_off)
        run(ServerOptions(), fn_off2)
        run(ServerOptions(cache_result_mb=8.0, cache_frame_mb=64.0,
                          cache_coalesce=True), fn_on)
        # deterministic encode: two uncached runs agree, and the cached
        # MISS path produces those same bytes (the cache may never alter
        # response bytes, only skip work)
        assert bodies["off"] == bodies["off2"]
        assert bodies["on"] == bodies["off"]


class TestHealthAndMetricsSurface:
    def test_cache_counters_in_health_and_metrics(self):
        async def fn(client, _origin, app):
            await client.post("/resize?width=100&height=66", data=jpg())
            await client.post("/resize?width=100&height=66", data=jpg())
            health = await (await client.get("/health")).json()
            assert health["cache"]["result_hits"] == 1
            assert health["cache"]["result_misses"] == 1
            assert health["cache"]["result_bytes"] > 0
            text = await (await client.get("/metrics")).text()
            assert "imaginary_tpu_cache_result_hits 1" in text
            assert "imaginary_tpu_cache_result_misses 1" in text

        run(ServerOptions(cache_result_mb=8.0), fn)
