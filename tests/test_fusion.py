"""Adjacent-resample fusion (ops/plan.py fuse_adjacent_shrinking_samples).

A /pipeline like crop(cover-resize) -> resize plans two full lanczos
passes; the first runs at near-source resolution for an intermediate no
one sees (~5 ms of the route's 12.7 ms host chain, measured). Fusion
collapses back-to-back pure-minification samples with matching kernels
into one direct resample — same map, equal-or-better antialiasing."""

import json

import numpy as np

from imaginary_tpu.options import ImageOptions
from imaginary_tpu.params import parse_json_operations
from imaginary_tpu.ops.plan import fuse_adjacent_shrinking_samples
from imaginary_tpu.ops.stages import SampleSpec
from imaginary_tpu.pipeline import _build_pipeline_plan


def _ops(*entries):
    return ImageOptions(operations=parse_json_operations(json.dumps(list(entries))))


def _sample_stages(plan):
    return [s for s in plan.stages if isinstance(s.spec, SampleSpec)]


class TestFusionPass:
    def test_crop_resize_chain_fuses_to_one_sample(self):
        o = _ops(
            {"operation": "crop", "params": {"width": 1600, "height": 900}},
            {"operation": "resize", "params": {"width": 640}},
            {"operation": "blur", "params": {"sigma": 1.5}},
        )
        plan, *_ = _build_pipeline_plan(o, 1080, 1920, 0, 3, None, None)
        assert len(_sample_stages(plan)) == 1
        st = _sample_stages(plan)[0]
        assert (int(st.dyn["dst_h"]), int(st.dyn["dst_w"])) == (360, 640)
        assert (plan.out_h, plan.out_w) == (360, 640)

    def test_three_way_cascade_fuses(self):
        o = _ops(
            {"operation": "resize", "params": {"width": 1200}},
            {"operation": "resize", "params": {"width": 800}},
            {"operation": "resize", "params": {"width": 200}},
        )
        plan, *_ = _build_pipeline_plan(o, 1080, 1920, 0, 3, None, None)
        assert len(_sample_stages(plan)) == 1
        assert plan.out_w == 200

    def test_enlarge_step_blocks_fusion(self):
        o = _ops(
            {"operation": "enlarge", "params": {"width": 2400, "height": 1350}},
            {"operation": "resize", "params": {"width": 640}},
        )
        plan, *_ = _build_pipeline_plan(o, 1080, 1920, 0, 3, None, None)
        # the enlarge pass changes frequency content the shrink then
        # consumes; collapsing would alter output beyond float noise
        assert len(_sample_stages(plan)) >= 2
        assert (plan.out_h, plan.out_w) == (360, 640)

    def test_intervening_stage_blocks_fusion(self):
        # crop with a REAL window -> sample + extract; a following resize
        # must not fuse across the extract
        o = _ops(
            {"operation": "crop", "params": {"width": 400, "height": 900}},
            {"operation": "resize", "params": {"width": 200}},
        )
        plan, *_ = _build_pipeline_plan(o, 1080, 1920, 0, 3, None, None)
        kinds = [type(s.spec).__name__ for s in plan.stages]
        assert "ExtractSpec" in kinds
        assert len(_sample_stages(plan)) == 2
        assert (plan.out_h, plan.out_w) == (450, 200)

    def test_kernel_mismatch_blocks_fusion(self):
        from imaginary_tpu.ops.plan import StageInstance

        def mk(h, w, kernel):
            return StageInstance(
                spec=SampleSpec(out_hb=h, out_wb=w, kernel=kernel),
                dyn={"dst_h": np.float32(h), "dst_w": np.float32(w)},
            )

        stages = [mk(500, 900, "lanczos3"), mk(200, 400, "nearest")]
        assert len(fuse_adjacent_shrinking_samples(stages, 1080, 1920)) == 2
        stages = [mk(500, 900, "lanczos3"), mk(200, 400, "lanczos3")]
        assert len(fuse_adjacent_shrinking_samples(stages, 1080, 1920)) == 1

    def test_fused_pixels_match_unfused(self, monkeypatch):
        """Fused output must stay close to the two-pass output on natural
        content (measured 54-63 dB on the photo fixtures). On pure random
        noise the two differ more (~30 dB): one-pass keeps high-frequency
        energy the two-pass chain's intermediate band-limit discards —
        fusion is the MORE faithful rendering of the source, so the gap
        is generation loss avoided, not error introduced."""
        import imaginary_tpu.ops.plan as plan_mod
        from imaginary_tpu import codecs
        from imaginary_tpu.engine import host_exec
        from tests.conftest import fixture_bytes, psnr

        d = codecs.decode(fixture_bytes("medium.jpg"), 1)
        h, w = d.array.shape[:2]
        o = _ops(
            {"operation": "crop", "params": {"width": int(w * 0.8), "height": int(h * 0.8)}},
            {"operation": "resize", "params": {"width": 256}},
        )
        fused, *_ = _build_pipeline_plan(o, h, w, 0, 3, None, None)
        monkeypatch.setattr(plan_mod, "fuse_adjacent_shrinking_samples",
                            lambda s, a, b: s)
        unfused, *_ = _build_pipeline_plan(o, h, w, 0, 3, None, None)
        assert len(_sample_stages(fused)) < len(_sample_stages(unfused))
        a = host_exec.run(d.array, fused)
        b = host_exec.run(d.array, unfused)
        assert a.shape == b.shape
        assert psnr(a, b) >= 45.0
