"""Per-device fault domains, hedged failover dispatch, and liveness
supervision (engine/devhealth.py + the ISSUE 6 executor/worker changes).

Covers: per-device breaker independence (chip k trips, its peers keep
serving), the quarantine -> probe -> re-admit cycle, hedge budget
enforcement + loser-cancellation ledger balance, the keyed
device.chip_error / worker.hang failpoint sites, supervisor hung-worker
kill/respawn at the subprocess level, and a parity pin that 1-device
registry behavior matches the PR 4 global-breaker semantics."""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from imaginary_tpu import failpoints
from imaginary_tpu.engine import Executor, ExecutorConfig
from imaginary_tpu.engine.devhealth import (
    STATE_HALF_OPEN,
    STATE_HEALTHY,
    STATE_QUARANTINED,
    DeviceHealthRegistry,
)
from imaginary_tpu.engine.executor import last_placement, reset_placement
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.ops.plan import plan_operation

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _img(h=96, w=128, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (h, w, 3), dtype=np.uint8)


def _plan(h=96, w=128, width=48):
    return plan_operation("resize", ImageOptions(width=width), h, w, 0, 3)


# --- registry unit behavior --------------------------------------------------


class TestRegistry:
    def test_breaker_independence(self):
        reg = DeviceHealthRegistry(4, threshold=3, cooldown_s=60)
        for _ in range(3):
            reg.note_failure(1, "chip 1 sick")
        assert reg.is_quarantined(1)
        assert not reg.is_quarantined(0)
        assert reg.healthy_indices() == [0, 2, 3]
        assert reg.any_available()
        # sticky pick skips the quarantined chip, never its peers
        assert reg.pick() == 0
        assert reg.pick(exclude={0}) == 2

    def test_one_device_parity_with_pr4_global_breaker(self):
        """The PR 4 semantics, spelled as assertions: trip on the Nth
        CONSECUTIVE failure, half-open at cooldown expiry, one more
        failure re-opens instantly, only a success resets."""
        reg = DeviceHealthRegistry(1, threshold=3, cooldown_s=0.2)
        assert reg.any_available()  # closed at rest
        reg.note_failure(0)
        reg.note_failure(0)
        assert reg.any_available()  # two strikes: still closed
        tripped = reg.note_failure(0)
        assert tripped and not reg.any_available()  # third: open
        rec = reg.record(0)
        assert rec.breaker_opens == 1
        # intervening success resets the count — PR 4's only reset path
        time.sleep(0.25)
        assert reg.any_available()  # half-open after cooldown
        assert rec.state(time.monotonic()) == STATE_HALF_OPEN
        # ONE more failure in the half-open window re-opens instantly
        assert reg.note_failure(0)
        assert not reg.any_available()
        time.sleep(0.25)
        reg.note_ok(0)
        assert rec.state(time.monotonic()) == STATE_HEALTHY
        assert rec.consecutive_failures == 0
        assert rec.readmissions == 1
        # closed means closed: a single new failure does not trip
        reg.note_failure(0)
        assert reg.any_available()

    def test_snapshot_shape(self):
        reg = DeviceHealthRegistry(2, threshold=1, cooldown_s=60)
        reg.note_failure(1, "boom")
        snap = reg.snapshot()
        assert snap["count"] == 2
        assert snap["healthy"] == 1
        assert snap["quarantined"] == 1
        states = {d["device"]: d["state"] for d in snap["per_device"]}
        assert states == {0: STATE_HEALTHY, 1: STATE_QUARANTINED}
        assert snap["per_device"][1]["last_error"] == "boom"

    def test_probe_readmits_and_respects_failures(self):
        reg = DeviceHealthRegistry(2, threshold=1, cooldown_s=0.1)
        sick = {1}

        def probe(idx):
            if idx in sick:
                raise RuntimeError("still sick")

        reg.note_failure(1)
        reg.start_probing(probe, timeout_s=2.0)
        try:
            time.sleep(0.5)
            # failing probes keep it quarantined (each failure re-opens)
            assert reg.record(1).probes >= 1
            assert not reg.healthy_indices() == [0, 1]
            sick.clear()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if reg.record(1).state(time.monotonic()) == STATE_HEALTHY:
                    break
                time.sleep(0.05)
            assert reg.record(1).state(time.monotonic()) == STATE_HEALTHY
            assert reg.record(1).readmissions == 1
        finally:
            reg.close()

    def test_hung_probe_books_a_failure(self):
        reg = DeviceHealthRegistry(2, threshold=1, cooldown_s=0.1)
        release = threading.Event()

        def probe(idx):
            release.wait(timeout=30)  # wedged inside the runtime

        reg.note_failure(1)
        before = reg.record(1).failures
        reg.start_probing(probe, timeout_s=0.3)
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if reg.record(1).failures > before:
                    break
                time.sleep(0.05)
            assert reg.record(1).failures > before
            assert not reg.is_quarantined(0)
        finally:
            release.set()
            reg.close()


# --- executor: chip failure -> failover -> quarantine -> re-admit ------------


class TestChipFailover:
    @pytest.fixture(autouse=True)
    def _need_multi_device(self):
        import jax

        if len(jax.local_devices()) < 2:
            pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")

    def test_sick_primary_fails_over_and_quarantines_alone(self, monkeypatch):
        """Chip 0 (the primary, device=None launches) dies; its chunks
        re-route to chip 1 and REQUESTS KEEP SUCCEEDING — losing one chip
        degrades capacity, not availability."""
        from imaginary_tpu.engine import executor as ex_mod
        from imaginary_tpu.obs import trace as obs_trace

        real = ex_mod.chain_mod.launch_batch

        def chip0_dead(arrs, plans, sharding=None, device=None):
            if device is None:  # the primary fault domain's launches
                raise RuntimeError("chip 0 down")
            return real(arrs, plans, sharding=sharding, device=device)

        monkeypatch.setattr(ex_mod.chain_mod, "launch_batch", chip0_dead)
        ex = Executor(ExecutorConfig(window_ms=1, host_spill=False,
                                     breaker_threshold=3,
                                     breaker_cooldown_s=60))
        try:
            tr = obs_trace.RequestTrace("req-failover")
            token = obs_trace.activate(tr)
            try:
                reset_placement()
                out = ex.process(_img(), _plan(), timeout=120)
            finally:
                obs_trace.deactivate(token)
            assert out.shape == (36, 48, 3)
            assert last_placement() == "device"  # served by chip 1, not host
            assert tr.fields["placement_attempts"] == [
                "device:0:error", "device:1"]
            # two more requests: chip 0 trips its own breaker...
            for i in range(2):
                ex.process(_img(seed=i + 1), _plan(), timeout=120)
            assert ex.devhealth.is_quarantined(0)
            snap = ex.devhealth.snapshot()
            assert snap["quarantined"] == 1
            assert snap["healthy"] == len(snap["per_device"]) - 1
            # ...the fleet never went down, so no global outage was booked
            assert not ex._breaker_is_open()
            assert ex.stats.breaker_opens == 0
            assert ex.stats.breaker_host_served == 0
            # quarantined primary is no longer attempted: one clean hop
            tr2 = obs_trace.RequestTrace("req-after-quarantine")
            token = obs_trace.activate(tr2)
            try:
                ex.process(_img(seed=9), _plan(), timeout=120)
            finally:
                obs_trace.deactivate(token)
            assert tr2.fields["placement_attempts"] == ["device:1"]
        finally:
            ex.shutdown()

    def test_chip_error_failpoint_quarantine_and_probe_readmission(self):
        """The chaos contract end-to-end: device.chip_error[0] kills the
        primary fault domain specifically, traffic fails over, the chip
        quarantines, and after the fault clears the background probe
        re-admits it within a cooldown."""
        failpoints.activate("device.chip_error[0]=error")
        ex = Executor(ExecutorConfig(window_ms=1, host_spill=False,
                                     breaker_threshold=2,
                                     breaker_cooldown_s=0.3))
        try:
            for i in range(2):
                out = ex.process(_img(seed=i), _plan(), timeout=120)
                assert out.shape == (36, 48, 3)
            assert ex.devhealth.is_quarantined(0)
            assert not ex._breaker_is_open()
            # counts surfaced on the keyed spelling
            snap = failpoints.snapshot()
            assert snap["sites"]["device.chip_error[0]"]["fired"] >= 2
            # while the fault is armed, probes FAIL: no re-admission flap
            time.sleep(0.8)
            assert ex.devhealth.record(0).state(time.monotonic()) != STATE_HEALTHY
            failpoints.deactivate()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if ex.devhealth.record(0).state(time.monotonic()) == STATE_HEALTHY:
                    break
                time.sleep(0.05)
            assert ex.devhealth.record(0).state(time.monotonic()) == STATE_HEALTHY
            assert ex.devhealth.record(0).readmissions >= 1
        finally:
            failpoints.deactivate()
            ex.shutdown()


# --- hedged failover dispatch ------------------------------------------------


class _BlockedDevice:
    """Monkeypatch helper: every launch blocks until released."""

    def __init__(self, monkeypatch):
        from imaginary_tpu.engine import executor as ex_mod

        self.release = threading.Event()
        real = ex_mod.chain_mod.launch_batch

        def blocked(*a, **k):
            self.release.wait(timeout=60)
            return real(*a, **k)

        monkeypatch.setattr(ex_mod.chain_mod, "launch_batch", blocked)


class TestHedging:
    def test_off_by_default_no_hedge_machinery(self):
        ex = Executor(ExecutorConfig(window_ms=1))
        try:
            fut = ex.submit(_img(), _plan())
            out = fut.result(timeout=120)
            assert out.shape == (36, 48, 3)
            assert not hasattr(fut, "_hedge_placement")
            assert ex.stats.hedges_launched == 0
        finally:
            ex.shutdown()

    def test_hedge_wins_over_stuck_device_and_ledger_balances(self, monkeypatch):
        blocked = _BlockedDevice(monkeypatch)
        ex = Executor(ExecutorConfig(window_ms=1, host_spill=False,
                                     hedge_threshold_ms=50.0))
        try:
            reset_placement()
            t0 = time.monotonic()
            out = ex.process(_img(), _plan(), timeout=30)
            dt_ms = (time.monotonic() - t0) * 1000.0
            assert out.shape == (36, 48, 3)
            assert last_placement() == "host"  # the twin's pixels
            assert ex.stats.hedges_won == 1
            # the request resolved at hedge latency, not device latency
            assert dt_ms < 10_000.0
            blocked.release.set()
            # cancelled loser released its owed-ms charge; after the
            # zombie drain finishes, the ledger is at rest
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                with ex._owed_lock:
                    if abs(ex._owed_ms) < 1e-6 and ex._device_items == 0:
                        break
                time.sleep(0.05)
            with ex._owed_lock:
                assert abs(ex._owed_ms) < 1e-6
                assert ex._device_items == 0
        finally:
            blocked.release.set()
            ex.shutdown()

    def test_hedge_budget_caps_concurrent_twins(self, monkeypatch):
        from imaginary_tpu.engine import executor as ex_mod

        blocked = _BlockedDevice(monkeypatch)
        # slow twins so they genuinely OVERLAP: the budget bounds
        # concurrency, and a fast twin that finishes before the next
        # timer fires frees its slot legitimately
        host_gate = threading.Event()
        real_host_run = ex_mod.host_exec.run

        def slow_host_run(arr, plan):
            host_gate.wait(timeout=30)
            return real_host_run(arr, plan)

        monkeypatch.setattr(ex_mod.host_exec, "run", slow_host_run)
        # budget 0.05 of 3 in-flight items floors at ONE concurrent hedge
        ex = Executor(ExecutorConfig(window_ms=1, host_spill=False,
                                     hedge_threshold_ms=50.0,
                                     hedge_budget=0.05))
        try:
            futs = [ex.submit(_img(seed=i), _plan()) for i in range(3)]
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if ex.stats.hedges_launched + ex.stats.hedges_skipped >= 3:
                    break
                time.sleep(0.02)
            assert ex.stats.hedges_launched == 1
            assert ex.stats.hedges_skipped == 2
            host_gate.set()
            blocked.release.set()
            for f in futs:
                f.result(timeout=60)
        finally:
            host_gate.set()
            blocked.release.set()
            ex.shutdown()

    def test_batch_class_is_never_hedged(self):
        ex = Executor(ExecutorConfig(window_ms=1, hedge_threshold_ms=50.0))
        try:
            from imaginary_tpu.engine.executor import _BATCH_CLASS, _Item
            from imaginary_tpu.qos import CLASS_INDEX

            assert _BATCH_CLASS == CLASS_INDEX["batch"]  # literal stays honest
            item = _Item(_img(), _plan())
            item.qos = ("hog", _BATCH_CLASS, 0.5, None)
            assert ex._arm_hedge(item) is None
            item.qos = ("vip", CLASS_INDEX["interactive"], 0.5, None)
            outer = ex._arm_hedge(item)
            assert outer is not None
            item.future.set_result(_img())  # resolve primary; timer cancels
            outer.result(timeout=5)
        finally:
            ex.shutdown()

    def test_device_error_while_twin_runs_surfaces_device_error(self, monkeypatch):
        """Both paths fail: the caller sees the DEVICE error (the twin
        was speculative), and nothing hangs."""
        from imaginary_tpu.engine import executor as ex_mod

        def dead(*a, **k):
            raise RuntimeError("device fell over")

        monkeypatch.setattr(ex_mod.chain_mod, "launch_batch", dead)
        monkeypatch.setattr(ex_mod.host_exec, "run",
                            lambda arr, plan: (_ for _ in ()).throw(
                                RuntimeError("twin also fell over")))
        ex = Executor(ExecutorConfig(window_ms=200, host_spill=False,
                                     hedge_threshold_ms=50.0,
                                     breaker_threshold=100))
        try:
            # window 200ms > hedge 50ms: the twin launches (and fails)
            # BEFORE the device dispatch fails — the stashed-error path
            with pytest.raises(RuntimeError, match="fell over"):
                ex.process(_img(), _plan(), timeout=30)
        finally:
            ex.shutdown()


# --- keyed failpoint grammar -------------------------------------------------


class TestKeyedFailpoints:
    def teardown_method(self):
        failpoints.deactivate()

    def test_keyed_site_parses_and_scopes(self):
        failpoints.activate("device.chip_error[1]=error")
        failpoints.hit("device.chip_error", key=0)  # other chip: no-op
        with pytest.raises(failpoints.FailpointError):
            failpoints.hit("device.chip_error", key=1)
        snap = failpoints.snapshot()
        assert snap["sites"]["device.chip_error[1]"]["fired"] == 1

    def test_bare_site_matches_every_key(self):
        failpoints.activate("device.chip_error=error")
        with pytest.raises(failpoints.FailpointError):
            failpoints.hit("device.chip_error", key=3)
        with pytest.raises(failpoints.FailpointError):
            failpoints.hit("device.chip_error")

    def test_unknown_base_site_rejected(self):
        with pytest.raises(ValueError, match="unknown failpoint site"):
            failpoints.parse("device.nope[1]=error")

    def test_worker_hang_site_delays_synchronously(self):
        failpoints.activate("worker.hang=delay(30ms)")
        t0 = time.monotonic()
        failpoints.hit("worker.hang")
        assert time.monotonic() - t0 >= 0.025


# --- supervisor liveness: hung worker is killed and replaced -----------------


def _health(port, timeout=2.0):
    import json
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/health", headers={"Connection": "close"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def test_supervisor_replaces_hung_worker():
    """Subprocess-level: SIGSTOP wedges one worker (alive, never
    answering — exactly what a hung accelerator runtime looks like from
    outside); the supervisor's liveness probe notices, spawns a
    replacement FIRST, then SIGTERM -> grace -> SIGKILLs the victim."""
    from tests.conftest import free_port

    port = free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("IMAGINARY_TPU_WORKER", None)
    # per-sample interval = PROBE_INTERVAL / workers = 0.2s; a healthy
    # worker unseen for the whole 6s window (while the hung listener
    # still eats ~1/3 of connections) is ~(2/3)^30 — not a flake source
    env["IMAGINARY_TPU_SUPERVISOR_PROBE_INTERVAL"] = "0.4"
    env["IMAGINARY_TPU_SUPERVISOR_LIVENESS_TIMEOUT"] = "6"
    env["IMAGINARY_TPU_SUPERVISOR_HANG_GRACE"] = "1.5"
    env["IMAGINARY_TPU_SUPERVISOR_BOOT_GRACE"] = "60"
    sup = subprocess.Popen(
        [sys.executable, "-m", "imaginary_tpu.cli", "--workers", "2",
         "--port", str(port)],
        cwd=ROOT, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        # wait for both workers to answer (their pids are the probe's view)
        pids = set()
        end = time.monotonic() + 90
        while time.monotonic() < end and len(pids) < 2:
            try:
                pids.add(_health(port)["pid"])
            except Exception:
                time.sleep(0.3)
        assert len(pids) == 2, f"fleet never fully up (saw {pids})"
        victim = sorted(pids)[0]
        os.kill(victim, signal.SIGSTOP)
        # the supervisor must notice the silence, replace, and reap
        end = time.monotonic() + 90
        replaced = False
        while time.monotonic() < end:
            seen = set()
            for _ in range(8):
                try:
                    seen.add(_health(port)["pid"])
                except Exception:
                    time.sleep(0.2)
            victim_dead = False
            try:
                os.kill(victim, 0)
            except OSError:
                victim_dead = True
            if victim_dead and len(seen) == 2 and victim not in seen:
                replaced = True
                break
            time.sleep(0.5)
        assert replaced, "hung worker was not killed and replaced"
    finally:
        if sup.poll() is None:
            sup.send_signal(signal.SIGTERM)
            try:
                sup.wait(timeout=20)
            except subprocess.TimeoutExpired:
                sup.kill()
                sup.wait()
