"""itpucheck: the project-invariant static analyzer (ISSUE 8).

Each rule gets a fixture pair — a snippet that TRIPS it and the
corrected spelling that doesn't — so the rule demonstrably fails
without the check and passes with it. Plus: the suppression grammar,
the JSON artifact schema, and the regression tripwire — the live repo
must produce zero unsuppressed findings (a future PR reintroducing an
unguarded set_exception or a time.sleep in an async def turns the gate
red before review ever sees it).
"""

import json
import os

from imaginary_tpu.tools.itpucheck import (
    default_paths,
    main,
    run_checks,
    to_json,
)


def _scan(tmp_path, sources, rules=None, readme=""):
    """Write {name: code} files under tmp_path, run the analyzer there."""
    for name, code in sources.items():
        path = tmp_path / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(code)
    if readme:
        (tmp_path / "README.md").write_text(readme)
    return run_checks(paths=[str(tmp_path)], root=str(tmp_path),
                      rules=rules)


def _rules_hit(findings):
    return {f.rule for f in findings}


# -- one fixture pair per rule ------------------------------------------------


class TestAsyncBlocking:
    def test_trips_on_sleep_and_sync_hit(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "import time\n"
            "from imaginary_tpu import failpoints\n"
            "async def handler(request):\n"
            "    time.sleep(1)\n"
            "    failpoints.hit('x')\n"
        )}, rules=["ITPU001"])
        assert [f.line for f in findings] == [4, 5]
        assert _rules_hit(findings) == {"ITPU001"}

    def test_clean_async_and_sync_sleep_pass(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "import asyncio, time\n"
            "from imaginary_tpu import failpoints\n"
            "async def handler(request):\n"
            "    await asyncio.sleep(1)\n"
            "    await failpoints.ahit('x')\n"
            "def sync_worker():\n"
            "    time.sleep(1)  # fine: not on the event loop\n"
            "async def offloaded():\n"
            "    def work():\n"
            "        time.sleep(1)  # nested def runs on a pool thread\n"
            "    return work\n"
        )}, rules=["ITPU001"])
        assert findings == []


class TestFutureGuard:
    def test_trips_unguarded(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "def resolve(fut, out):\n"
            "    fut.set_result(out)\n"
            "def fail(fut, e):\n"
            "    fut.set_exception(e)\n"
        )}, rules=["ITPU002"])
        assert [f.line for f in findings] == [2, 4]

    def test_done_guard_and_try_pass(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "from concurrent.futures import InvalidStateError\n"
            "def resolve(fut, out):\n"
            "    if not fut.done():\n"
            "        fut.set_result(out)\n"
            "def fail(fut, e):\n"
            "    try:\n"
            "        fut.set_exception(e)\n"
            "    except InvalidStateError:\n"
            "        pass\n"
        )}, rules=["ITPU002"])
        assert findings == []

    def test_guard_does_not_cross_function_boundary(self, tmp_path):
        # a done() check in the OUTER function must not bless a nested
        # callback's unguarded resolution
        findings, _ = _scan(tmp_path, {"m.py": (
            "def outer(fut):\n"
            "    if not fut.done():\n"
            "        def cb(f):\n"
            "            fut.set_result(1)\n"
            "        return cb\n"
        )}, rules=["ITPU002"])
        assert [f.line for f in findings] == [4]


class TestLedger:
    def test_trips_charge_without_finally(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Ex:\n"
            "    def submit(self, item):\n"
            "        self._host_charge(item.mpix)\n"
            "        out = self.run(item)\n"
            "        self._host_release(item.mpix)\n"  # not in a finally
            "        return out\n"
        )}, rules=["ITPU003"])
        assert [f.line for f in findings] == [3]

    def test_finally_release_passes(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Ex:\n"
            "    def submit(self, item):\n"
            "        self._host_charge(item.mpix)\n"
            "        try:\n"
            "            return self.run(item)\n"
            "        finally:\n"
            "            self._host_release(item.mpix)\n"
        )}, rules=["ITPU003"])
        assert findings == []

    def test_trips_owed_charge_without_cancel(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Ex:\n"
            "    def submit(self, item):\n"
            "        self._charge_owed(item)\n"
            "        self._queue.put(item)\n"  # a raising put leaks
            "        return item.future\n"
        )}, rules=["ITPU003"])
        assert [f.line for f in findings] == [3]

    def test_cancel_on_enqueue_failure_passes(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Ex:\n"
            "    def submit(self, item):\n"
            "        self._charge_owed(item)\n"
            "        try:\n"
            "            self._queue.put(item)\n"
            "        except Exception:\n"
            "            item.future.cancel()\n"
            "            raise\n"
            "        return item.future\n"
        )}, rules=["ITPU003"])
        assert findings == []


class TestLaneLedger:
    def test_trips_lane_charge_without_finally(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Ex:\n"
            "    def _lane_fetch(self, lane):\n"
            "        _lane_charge(lane, 4)\n"
            "        outs = self.drain(lane)\n"
            "        _lane_release(lane, 4)\n"  # not in a finally
            "        return outs\n"
        )}, rules=["ITPU011"])
        assert [f.line for f in findings] == [3]

    def test_finally_release_passes(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Ex:\n"
            "    def _lane_fetch(self, lane):\n"
            "        _lane_charge(lane, 4)\n"
            "        try:\n"
            "            return self.drain(lane)\n"
            "        finally:\n"
            "            _lane_release(lane, 4)\n"
        )}, rules=["ITPU011"])
        assert findings == []

    def test_trips_owe_without_cancel(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Ex:\n"
            "    def submit(self, item, lane):\n"
            "        _lane_owe(lane, item)\n"
            "        lane.put(item)\n"  # a raising put strands the charge
            "        return item.future\n"
        )}, rules=["ITPU011"])
        assert [f.line for f in findings] == [3]

    def test_cancel_on_enqueue_failure_passes(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Ex:\n"
            "    def submit(self, item, lane):\n"
            "        _lane_owe(lane, item)\n"
            "        try:\n"
            "            lane.put(item)\n"
            "        except Exception:\n"
            "            item.future.cancel()\n"
            "            raise\n"
            "        return item.future\n"
        )}, rules=["ITPU011"])
        assert findings == []


class TestSilentExcept:
    def test_trips_both_shapes(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        pass\n"
            "def h():\n"
            "    try:\n"
            "        g()\n"
            "    except:\n"
            "        return None\n"
        )}, rules=["ITPU004"])
        assert [f.line for f in findings] == [4, 9]

    def test_narrow_or_handled_passes(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except ValueError:\n"
            "        pass\n"  # narrowed: fine
            "    try:\n"
            "        g()\n"
            "    except Exception as e:\n"
            "        log(e)\n"  # handled: fine
        )}, rules=["ITPU004"])
        assert findings == []


class TestConfigSurface:
    def test_trips_missing_env_and_readme(self, tmp_path):
        findings, _ = _scan(tmp_path, {"cli.py": (
            "import argparse, os\n"
            "p = argparse.ArgumentParser()\n"
            "p.add_argument('--shiny-knob', default='')\n"
            "SECRET = os.environ.get('IMAGINARY_TPU_UNDOCUMENTED', '')\n"
        )}, rules=["ITPU005"], readme="# docs\nnothing relevant\n")
        msgs = "\n".join(f.message for f in findings)
        assert "IMAGINARY_TPU_SHINY_KNOB" in msgs       # env default missing
        assert "--shiny-knob" in msgs                   # README mention missing
        assert "IMAGINARY_TPU_UNDOCUMENTED" in msgs     # env not in README
        assert len(findings) == 3

    def test_consistent_surface_passes(self, tmp_path):
        findings, _ = _scan(tmp_path, {"cli.py": (
            "import argparse, os\n"
            "p = argparse.ArgumentParser()\n"
            "p.add_argument('--shiny-knob',\n"
            "               default=os.environ.get('IMAGINARY_TPU_SHINY_KNOB', ''))\n"
        )}, rules=["ITPU005"],
            readme="`--shiny-knob` / `IMAGINARY_TPU_SHINY_KNOB`\n")
        assert findings == []


class TestFailpointRegistry:
    _REGISTRY = "SITES = (\n    'source.fetch',\n    'codec.decode',\n)\n"

    def test_trips_unknown_and_unused(self, tmp_path):
        findings, _ = _scan(tmp_path, {
            "failpoints.py": self._REGISTRY,
            "m.py": (
                "from imaginary_tpu import failpoints\n"
                "def f():\n"
                "    failpoints.hit('source.fetch')\n"
                "    failpoints.hit('typo.site')\n"
            ),
        }, rules=["ITPU006"])
        msgs = "\n".join(f.message for f in findings)
        assert "typo.site" in msgs          # used but undeclared
        assert "codec.decode" in msgs       # declared but never hit
        assert len(findings) == 2

    def test_registry_in_sync_passes(self, tmp_path):
        findings, _ = _scan(tmp_path, {
            "failpoints.py": self._REGISTRY,
            "m.py": (
                "from imaginary_tpu import failpoints\n"
                "async def f():\n"
                "    await failpoints.ahit('source.fetch')\n"
                "def g():\n"
                "    failpoints.hit('codec.decode')\n"
            ),
        }, rules=["ITPU006"])
        assert findings == []


class TestMetricsExposition:
    def test_trips_all_three_contracts(self, tmp_path):
        findings, _ = _scan(tmp_path, {"web/metrics.py": (
            "def render(x, v):\n"
            "    x.emit('myapp_requests', v, help_text='h')\n"
            "    x.emit('imaginary_tpu_errors', v, mtype='counter',\n"
            "           help_text='h')\n"
            "    x.emit('imaginary_tpu_depth', v)\n"
        )}, rules=["ITPU007"])
        msgs = "\n".join(f.message for f in findings)
        assert "namespace" in msgs          # myapp_ prefix
        assert "_total" in msgs             # counter naming
        assert "help_text" in msgs          # HELP line
        assert len(findings) == 3

    def test_strict_families_pass(self, tmp_path):
        findings, _ = _scan(tmp_path, {"web/metrics.py": (
            "def render(x, v, k):\n"
            "    x.emit('imaginary_tpu_errors_total', v, mtype='counter',\n"
            "           help_text='Errors.')\n"
            "    x.emit('imaginary_tpu_depth', v, help_text='Depth.')\n"
            "    x.emit(f'imaginary_tpu_exec_{k}', v, mtype=k,\n"
            "           help_text='Dynamic family.')\n"
        )}, rules=["ITPU007"])
        assert findings == []


class TestContextPropagation:
    def test_trips_bare_pool_submit_and_run_in_executor(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "async def handle(self, loop, work):\n"
            "    fut = self.pool.submit(work, 1)\n"
            "    await loop.run_in_executor(None, work)\n"
        )}, rules=["ITPU008"])
        assert [f.line for f in findings] == [2, 3]

    def test_copy_context_passes(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "import contextvars\n"
            "async def handle(self, loop, work):\n"
            "    ctx = contextvars.copy_context()\n"
            "    fut = self.pool.submit(ctx.run, work, 1)\n"
            "    await loop.run_in_executor(None, ctx.run, work)\n"
            "    self.executor.submit(work, 1)  # micro-batch executor, not a pool\n"
        )}, rules=["ITPU008"])
        assert findings == []


class TestSlotProtocol:
    def test_trips_acquire_without_finally_abandon(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Cache:\n"
            "    def put(self, idx, body):\n"
            "        slot = self._slot_acquire(idx)\n"
            "        self._write(slot, body)\n"  # a raise leaks the lock
            "        self._slot_publish(slot)\n"
        )}, rules=["ITPU009"])
        assert [f.line for f in findings] == [3]
        assert _rules_hit(findings) == {"ITPU009"}

    def test_trips_abandon_in_except_not_finally(self, tmp_path):
        # an except-only abandon misses the success path's unlock AND
        # non-Exception exits; the protocol demands a finally
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Cache:\n"
            "    def put(self, idx, body):\n"
            "        slot = self._slot_acquire(idx)\n"
            "        try:\n"
            "            self._slot_publish(slot)\n"
            "        except Exception:\n"
            "            self._slot_abandon(slot)\n"
        )}, rules=["ITPU009"])
        assert [f.line for f in findings] == [3]

    def test_publish_then_abandon_in_finally_passes(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Cache:\n"
            "    def put(self, idx, body):\n"
            "        slot = self._slot_acquire(idx)\n"
            "        if slot is None:\n"
            "            return False\n"
            "        try:\n"
            "            self._write(slot, body)\n"
            "            self._slot_publish(slot)\n"
            "            return True\n"
            "        finally:\n"
            "            self._slot_abandon(slot)\n"
        )}, rules=["ITPU009"])
        assert findings == []

    def test_primitives_themselves_exempt(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Cache:\n"
            "    def _slot_acquire(self, idx):\n"
            "        return self._slot_acquire(idx - 1) if idx else None\n"
            "    def _slot_abandon(self, slot):\n"
            "        self._unlock(slot.idx)\n"
        )}, rules=["ITPU009"])
        assert findings == []


class TestClaimProtocol:
    def test_trips_acquire_without_finally_release(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "async def run(shm, key, produce):\n"
            "    claim = shm.claim_acquire(key)\n"
            "    out = await produce()\n"  # a raise strands the claim
            "    shm.claim_release(claim)\n"
            "    return out\n"
        )}, rules=["ITPU013"])
        assert [f.line for f in findings] == [2]
        assert _rules_hit(findings) == {"ITPU013"}

    def test_trips_release_in_except_not_finally(self, tmp_path):
        # an except-only release misses the success path AND
        # non-Exception exits (CancelledError on 3.8+ is BaseException);
        # the protocol demands a finally
        findings, _ = _scan(tmp_path, {"m.py": (
            "async def run(shm, key, produce):\n"
            "    claim = shm.claim_acquire(key)\n"
            "    try:\n"
            "        return await produce()\n"
            "    except Exception:\n"
            "        shm.claim_release(claim)\n"
            "        raise\n"
        )}, rules=["ITPU013"])
        assert [f.line for f in findings] == [2]

    def test_release_in_finally_passes(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "async def run(shm, key, produce):\n"
            "    claim = shm.claim_acquire(key)\n"
            "    try:\n"
            "        if claim.won:\n"
            "            return await produce()\n"
            "    finally:\n"
            "        shm.claim_release(claim)\n"
            "    return None\n"
        )}, rules=["ITPU013"])
        assert findings == []

    def test_abandon_in_finally_passes(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "def probe(shm, key):\n"
            "    claim = shm.claim_acquire(key)\n"
            "    try:\n"
            "        return claim.won\n"
            "    finally:\n"
            "        shm.claim_abandon(claim)\n"
        )}, rules=["ITPU013"])
        assert findings == []

    def test_primitives_themselves_exempt(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "class Shm:\n"
            "    def claim_acquire(self, key):\n"
            "        return self._claim(self.claim_index(key))\n"
            "    def claim_release(self, claim):\n"
            "        self._unlock(claim.idx)\n"
        )}, rules=["ITPU013"])
        assert findings == []


class TestObsRegistry:
    def test_trips_all_five_directions(self, tmp_path):
        findings, _ = _scan(tmp_path, {
            "events.py": (
                "SAMPLED_REASONS = (\n"
                "    'error',\n"
                "    'random',\n"
                "    'stale_entry',\n"
                ")\n"
                "def classify(event):\n"
                "    if event.get('status', 0) >= 400:\n"
                "        return 'error'\n"
                "    if event.get('typo'):\n"
                "        return 'typo_reason'\n"
                "    return 'random'\n"
            ),
            "slo.py": (
                "SLO_METRICS = (\n"
                "    'imaginary_tpu_slo_burn_rate',\n"
                "    'imaginary_tpu_slo_ghost',\n"
                ")\n"
            ),
            "m.py": (
                "def f(x, event, v):\n"
                "    if event['sampled_reason'] == 'nonsense':\n"
                "        return 1\n"
                "    x.emit('imaginary_tpu_slo_burn_rate', v)\n"
                "    x.emit('imaginary_tpu_slo_typo_total', v)\n"
            ),
        }, rules=["ITPU010"])
        msgs = "\n".join(f.message for f in findings)
        assert "typo_reason" in msgs         # classify mints undeclared
        assert "nonsense" in msgs            # compared-against undeclared
        assert "stale_entry" in msgs         # declared, never used
        assert "imaginary_tpu_slo_typo_total" in msgs  # rendered undeclared
        assert "imaginary_tpu_slo_ghost" in msgs       # declared, unrendered
        assert len(findings) == 5
        assert _rules_hit(findings) == {"ITPU010"}

    def test_registries_in_sync_pass(self, tmp_path):
        findings, _ = _scan(tmp_path, {
            "events.py": (
                "SAMPLED_REASONS = (\n"
                "    'error',\n"
                "    'random',\n"
                "    'unsampled',\n"
                ")\n"
                "def classify(event):\n"
                "    if event.get('status', 0) >= 400:\n"
                "        return 'error'\n"
                "    return 'random'\n"
            ),
            "slo.py": (
                "SLO_METRICS = (\n"
                "    'imaginary_tpu_slo_burn_rate',\n"
                ")\n"
            ),
            "m.py": (
                "def f(x, ev, v):\n"
                "    if ev.get('sampled_reason') != 'unsampled':\n"
                "        x.emit_line(ev)\n"
                "    x.emit('imaginary_tpu_slo_burn_rate', v)\n"
            ),
        }, rules=["ITPU010"])
        assert findings == []

    def test_silent_without_registry_modules(self, tmp_path):
        # a tree without the registries (e.g. a partial scan of one
        # subpackage) must not crash or spray findings
        findings, _ = _scan(tmp_path, {"m.py": (
            "def f(ev):\n"
            "    return ev.get('sampled_reason')\n"
        )}, rules=["ITPU010"])
        assert findings == []


class TestLabelCardinality:
    _COST = (
        "_LABEL_KINDS = ('tenant', 'op', 'route', 'qos_class')\n"
        "def normalize_label(kind, value):\n"
        "    return value\n"
    )

    def test_trips_unnormalized_guarded_label(self, tmp_path):
        findings, _ = _scan(tmp_path, {
            "obs/cost.py": self._COST,
            "web/metrics.py": (
                "def render(x, tenants, esc):\n"
                "    for t, v in tenants.items():\n"
                "        x.emit('imaginary_tpu_cost_requests_total', v,\n"
                "               f'tenant=\"{esc(t)}\"', mtype='counter',\n"
                "               help_text='h')\n"
            ),
        }, rules=["ITPU012"])
        assert _rules_hit(findings) == {"ITPU012"}
        assert "tenant=" in findings[0].message
        assert "normalize_label" in findings[0].message

    def test_trips_undeclared_kind(self, tmp_path):
        findings, _ = _scan(tmp_path, {
            "obs/cost.py": self._COST,
            "m.py": (
                "from obs.cost import normalize_label\n"
                "def f(v):\n"
                "    return normalize_label('flavor', v)\n"
            ),
        }, rules=["ITPU012"])
        assert _rules_hit(findings) == {"ITPU012"}
        assert "'flavor'" in findings[0].message
        assert "_LABEL_KINDS" in findings[0].message

    def test_normalized_chain_passes(self, tmp_path):
        # both spellings pass: inline call, and a variable assigned from
        # an escape(normalize_label(...)) chain — the live metrics.py
        # idiom for the slo route labels
        findings, _ = _scan(tmp_path, {
            "obs/cost.py": self._COST,
            "web/metrics.py": (
                "from obs.cost import normalize_label\n"
                "def render(x, tenants, routes, esc, v):\n"
                "    for t in tenants:\n"
                "        lab = esc(normalize_label('tenant', t))\n"
                "        x.emit('imaginary_tpu_cost_requests_total', v,\n"
                "               f'tenant=\"{lab}\"', mtype='counter',\n"
                "               help_text='h')\n"
                "    for r in routes:\n"
                "        x.emit('imaginary_tpu_slo_burn_rate', v,\n"
                "               f'route=\"{esc(normalize_label(\"route\", r))}\"',\n"
                "               help_text='h')\n"
            ),
        }, rules=["ITPU012"])
        assert findings == []

    def test_unguarded_keys_stay_free(self, tmp_path):
        # class=/lane=/stage= are bounded enums: no normalizer required
        findings, _ = _scan(tmp_path, {
            "obs/cost.py": self._COST,
            "web/metrics.py": (
                "def render(x, classes, esc, v):\n"
                "    for c in classes:\n"
                "        x.emit('imaginary_tpu_qos_shed_total', v,\n"
                "               f'class=\"{esc(c)}\"', mtype='counter',\n"
                "               help_text='h')\n"
            ),
        }, rules=["ITPU012"])
        assert findings == []

    def test_missing_registry_is_a_finding(self, tmp_path):
        # normalize_label used but no _LABEL_KINDS registry in the tree:
        # the contract has no owner
        findings, _ = _scan(tmp_path, {"m.py": (
            "from obs.cost import normalize_label\n"
            "def f(v):\n"
            "    return normalize_label('tenant', v)\n"
        )}, rules=["ITPU012"])
        assert _rules_hit(findings) == {"ITPU012"}


class TestPeerTimeout:
    def test_trips_urlopen_and_session_verbs_without_timeout(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "import urllib.request\n"
            "def gossip(url, session):\n"
            "    urllib.request.urlopen(url)\n"  # no timeout at all
            "    session.get(url, timeout=None)\n"  # unbounded, spelled out
            "    session.post(url)\n"
        )}, rules=["ITPU014"])
        assert [f.line for f in findings] == [3, 4, 5]
        assert _rules_hit(findings) == {"ITPU014"}

    def test_aiohttp_oneshot_request_trips(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "import aiohttp\n"
            "async def hop(url):\n"
            "    async with aiohttp.request('GET', url) as r:\n"
            "        return await r.read()\n"
        )}, rules=["ITPU014"])
        assert [f.line for f in findings] == [3]

    def test_bounded_calls_pass(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "import urllib.request\n"
            "import aiohttp\n"
            "async def hop(url, session, budget):\n"
            "    urllib.request.urlopen(url, timeout=1.0)\n"
            "    session.get(url, timeout=budget)\n"
            "    async with aiohttp.request('GET', url,\n"
            "            timeout=aiohttp.ClientTimeout(total=budget)) as r:\n"
            "        return await r.read()\n"
        )}, rules=["ITPU014"])
        assert findings == []

    def test_plain_dict_get_is_not_http(self, tmp_path):
        # the rule is about sockets, not maps: obj.get()/cache.get()
        # without timeout= must never trip
        findings, _ = _scan(tmp_path, {"m.py": (
            "def read(table, peers, key):\n"
            "    a = table.get(key)\n"
            "    b = peers.get(key, None)\n"
            "    return a or b\n"
        )}, rules=["ITPU014"])
        assert findings == []


# -- suppression grammar ------------------------------------------------------


class TestSuppression:
    _CODE = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # itpu: allow[ITPU001] measured: must block here\n"
    )

    def test_same_line_suppression(self, tmp_path):
        findings, suppressed = _scan(tmp_path, {"m.py": self._CODE},
                                     rules=["ITPU001"])
        assert findings == []
        assert len(suppressed) == 1
        assert suppressed[0].reason == "measured: must block here"

    def test_standalone_comment_covers_next_code_line(self, tmp_path):
        findings, suppressed = _scan(tmp_path, {"m.py": (
            "import time\n"
            "async def f():\n"
            "    # itpu: allow[ITPU001] deliberate wedge simulation\n"
            "    time.sleep(1)\n"
        )}, rules=["ITPU001"])
        assert findings == []
        assert len(suppressed) == 1

    def test_reasonless_suppression_is_a_finding(self, tmp_path):
        findings, suppressed = _scan(tmp_path, {"m.py": (
            "import time\n"
            "async def f():\n"
            "    time.sleep(1)  # itpu: allow[ITPU001]\n"
        )}, rules=["ITPU001"])
        # the blanket suppression does NOT suppress, and is itself flagged
        rules = sorted(f.rule for f in findings)
        assert rules == ["ITPU000", "ITPU001"]
        assert suppressed == []

    def test_wrong_rule_id_does_not_suppress(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "import time\n"
            "async def f():\n"
            "    time.sleep(1)  # itpu: allow[ITPU004] wrong rule named\n"
        )}, rules=["ITPU001"])
        assert {f.rule for f in findings} == {"ITPU001"}

    def test_unknown_rule_id_is_a_finding(self, tmp_path):
        findings, _ = _scan(tmp_path, {"m.py": (
            "x = 1  # itpu: allow[BOGUS123] whatever\n"
        )})
        assert any(f.rule == "ITPU000" and "BOGUS123" in f.message
                   for f in findings)


# -- output surfaces ----------------------------------------------------------


class TestJsonOutput:
    def test_schema(self, tmp_path):
        (tmp_path / "m.py").write_text(
            "import time\nasync def f():\n    time.sleep(1)\n")
        out = tmp_path / "artifacts" / "itpucheck.json"
        rc = main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                   "--json", str(out), "-q"])
        assert rc == 1
        doc = json.loads(out.read_text())
        assert doc["tool"] == "itpucheck"
        assert doc["version"] == 1
        assert set(doc["counts"]) == {"findings", "suppressed", "per_rule"}
        assert doc["counts"]["findings"] == len(doc["findings"]) == 1
        f = doc["findings"][0]
        assert set(f) == {"rule", "path", "line", "message"}
        assert f["rule"] == "ITPU001" and f["line"] == 3
        # all 14 rules are advertised in the rule table
        assert len([r for r in doc["rules"] if r != "ITPU000"]) == 14

    def test_to_json_counts_suppressed(self, tmp_path):
        findings, suppressed = _scan(tmp_path, {"m.py": (
            "import time\n"
            "async def f():\n"
            "    time.sleep(1)  # itpu: allow[ITPU001] fixture\n"
        )}, rules=["ITPU001"])
        doc = to_json(findings, suppressed)
        assert doc["counts"]["suppressed"] == 1
        assert doc["suppressed_findings"][0]["reason"] == "fixture"

    def test_exit_zero_and_artifact_on_clean_tree(self, tmp_path):
        (tmp_path / "m.py").write_text("x = 1\n")
        out = tmp_path / "r.json"
        rc = main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                   "--json", str(out), "-q"])
        assert rc == 0
        assert json.loads(out.read_text())["counts"]["findings"] == 0


class TestSyntaxError:
    def test_unparseable_file_is_a_finding(self, tmp_path):
        (tmp_path / "m.py").write_text("def broken(:\n")
        findings, _ = run_checks(paths=[str(tmp_path)], root=str(tmp_path))
        assert [f.rule for f in findings] == ["ITPU000"]
        assert "syntax error" in findings[0].message


# -- the regression tripwire --------------------------------------------------


class TestLiveTree:
    def test_live_tree_is_clean(self):
        """The shipped package has an EMPTY baseline: zero unsuppressed
        findings. Reintroducing any encoded bug class — an unguarded
        set_exception, a time.sleep in an async def, a leaking ledger
        charge, an off-registry failpoint — fails here (and `make
        check`) immediately."""
        findings, suppressed = run_checks()
        assert findings == [], "\n" + "\n".join(f.render() for f in findings)
        # every in-tree suppression carries a reason (ITPU000 enforces
        # this, but pin it explicitly: it is the review contract)
        assert all(f.reason for f in suppressed)

    def test_default_scan_covers_the_package(self):
        paths, root = default_paths()
        assert os.path.basename(paths[0]) == "imaginary_tpu"
        assert os.path.isfile(os.path.join(root, "README.md"))
