"""Failpoint harness tests (imaginary_tpu/failpoints.py) + the chaos
scenarios ISSUE-4 names: every injection site reachable, flaky origin
converging through retries, dead origin mapping to 502 within budget,
faults mid-coalesce fanning out to all waiters, breaker failover under
injected device errors, and cache faults degrading to misses — with the
harness itself provably free when disarmed."""

import asyncio
import time

import pytest

from imaginary_tpu import failpoints
from imaginary_tpu.web.config import ServerOptions
from tests.conftest import fixture_bytes
from tests.test_server import multipart_jpg, run


@pytest.fixture(autouse=True)
def _disarm():
    failpoints.deactivate()
    yield
    failpoints.deactivate()


@pytest.fixture(scope="module", autouse=True)
def _fixtures(testdata):
    return testdata


class TestSpecParsing:
    def test_basic_clauses(self):
        parsed = failpoints.parse(
            "source.fetch=error(0.5);device.execute=delay(200ms)")
        assert parsed["source.fetch"].kind == "error"
        assert parsed["source.fetch"].p == 0.5
        assert parsed["device.execute"].kind == "delay"
        assert parsed["device.execute"].duration_s == pytest.approx(0.2)

    def test_error_defaults_p1(self):
        assert failpoints.parse("codec.decode=error")["codec.decode"].p == 1.0

    def test_durations(self):
        assert failpoints.parse("cache.get=delay(1.5s)")["cache.get"].duration_s == 1.5
        assert failpoints.parse("cache.get=timeout(50ms)")["cache.get"].duration_s == 0.05
        assert failpoints.parse("cache.get=timeout")["cache.get"].duration_s == 60.0

    def test_once_wrapper(self):
        sp = failpoints.parse("source.fetch=once(error)")["source.fetch"]
        assert sp.kind == "error" and sp.once

    def test_unknown_site_rejected(self):
        with pytest.raises(ValueError, match="unknown failpoint site"):
            failpoints.parse("bogus.site=error")

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError, match="unknown failpoint action"):
            failpoints.parse("source.fetch=explode")

    def test_malformed_rejected(self):
        with pytest.raises(ValueError):
            failpoints.parse("source.fetch")
        with pytest.raises(ValueError):
            failpoints.parse("source.fetch=delay")  # delay needs a duration
        with pytest.raises(ValueError):
            failpoints.parse("source.fetch=error(2.0)")  # p outside [0,1]
        with pytest.raises(ValueError):
            failpoints.parse("source.fetch=delay(10)")  # unit required

    def test_empty_spec_disarms(self):
        failpoints.activate("source.fetch=error")
        failpoints.activate("")
        assert not failpoints.snapshot()["enabled"]

    def test_active_spec_round_trips(self):
        spec = "source.fetch=error(0.5);device.execute=delay(200ms)"
        failpoints.activate(spec)
        assert failpoints.parse(failpoints.active_spec()).keys() == \
            failpoints.parse(spec).keys()

    def test_activate_from_env(self):
        assert not failpoints.activate_from_env({"OTHER": "x"})
        assert failpoints.activate_from_env(
            {failpoints.ENV_VAR: "codec.encode=error"})
        assert failpoints.snapshot()["sites"]["codec.encode"]["action"] == "error"

    def test_bad_env_spec_fails_loudly(self):
        with pytest.raises(ValueError):
            failpoints.activate_from_env({failpoints.ENV_VAR: "nope=error"})


class TestActionsAndOverhead:
    def test_disarmed_is_noop(self):
        failpoints.hit("source.fetch")  # nothing raised
        asyncio.run(failpoints.ahit("source.fetch"))

    def test_disarmed_overhead_negligible(self):
        """The off path is one falsy-dict check: 200k calls must be far
        under human-visible time (generous bound for noisy CI hosts)."""
        t0 = time.monotonic()
        for _ in range(200_000):
            failpoints.hit("codec.decode")
        assert time.monotonic() - t0 < 1.0

    def test_error_raises(self):
        failpoints.activate("codec.decode=error")
        with pytest.raises(failpoints.FailpointError):
            failpoints.hit("codec.decode")
        # other sites untouched
        failpoints.hit("codec.encode")

    def test_error_probability_zero_never_fires(self):
        failpoints.activate("codec.decode=error(0.0)")
        for _ in range(100):
            failpoints.hit("codec.decode")
        snap = failpoints.snapshot()["sites"]["codec.decode"]
        assert snap["hits"] == 100 and snap["fired"] == 0

    def test_once_fires_exactly_once(self):
        failpoints.activate("codec.decode=once(error)")
        with pytest.raises(failpoints.FailpointError):
            failpoints.hit("codec.decode")
        failpoints.hit("codec.decode")  # spent: no-op
        snap = failpoints.snapshot()
        assert snap["sites"]["codec.decode"]["fired"] == 1

    def test_delay_sleeps_then_continues(self):
        failpoints.activate("codec.decode=delay(50ms)")
        t0 = time.monotonic()
        failpoints.hit("codec.decode")  # no raise
        assert time.monotonic() - t0 >= 0.045

    def test_timeout_sync_raises_timeout_error(self):
        failpoints.activate("codec.decode=timeout(10ms)")
        with pytest.raises(TimeoutError):
            failpoints.hit("codec.decode")

    def test_timeout_async_raises_asyncio_timeout(self):
        failpoints.activate("source.fetch=timeout(10ms)")
        with pytest.raises(asyncio.TimeoutError):
            asyncio.run(failpoints.ahit("source.fetch"))


class TestEverySiteReachable:
    """Arm each site with error(1.0) and observe its documented effect
    through the real serving stack — reachability AND the degradation
    policy at that boundary."""

    def test_source_fetch_site(self):
        from aiohttp import web as aioweb

        async def origin(request):
            return aioweb.Response(body=fixture_bytes("imaginary.jpg"),
                                   content_type="image/jpeg")

        failpoints.activate("source.fetch=once(error)")

        async def fn(client, origin_url):
            # first attempt eats the injected fault; the retry serves
            res = await client.get(f"/resize?width=100&url={origin_url}/i.jpg")
            assert res.status == 200
            assert failpoints.snapshot()["sites"]["source.fetch"]["fired"] == 1

        run(ServerOptions(enable_url_source=True), fn, origin_handler=origin)

    def test_source_head_site_degrades(self):
        from aiohttp import web as aioweb

        async def origin(request):
            return aioweb.Response(body=fixture_bytes("imaginary.jpg"),
                                   content_type="image/jpeg")

        failpoints.activate("source.head=error")

        async def fn(client, origin_url):
            # HEAD pre-check faulted -> size-capped GET serves anyway
            res = await client.get(f"/resize?width=100&url={origin_url}/i.jpg")
            assert res.status == 200
            assert failpoints.snapshot()["sites"]["source.head"]["fired"] >= 1

        run(ServerOptions(enable_url_source=True, max_allowed_size=10_000_000),
            fn, origin_handler=origin)

    def test_codec_decode_site(self):
        failpoints.activate("codec.decode=error")

        async def fn(client, _):
            res = await client.post("/resize?width=100",
                                    data=fixture_bytes("imaginary.jpg"))
            assert res.status == 400
            body = await res.json()
            assert "injected error" in body["message"]

        run(ServerOptions(), fn)

    def test_executor_submit_site(self):
        failpoints.activate("executor.submit=error")

        async def fn(client, _):
            res = await client.post("/resize?width=100",
                                    data=fixture_bytes("imaginary.jpg"))
            assert res.status == 400

        run(ServerOptions(), fn)

    def test_device_execute_site_trips_breaker_to_host(self):
        """Injected device failures exercise the availability story
        end-to-end: errors surface per-request until the breaker's
        consecutive-failure threshold, then host failover serves 200s."""
        failpoints.activate("device.execute=error")

        async def fn(client, _):
            svc = client.app["service"]
            statuses = []
            for _ in range(6):
                res = await client.post("/resize?width=100",
                                        data=fixture_bytes("imaginary.jpg"))
                statuses.append(res.status)
                if res.status == 200:
                    assert res.headers.get("X-Imaginary-Backend") == "host"
                    break
            assert statuses[-1] == 200, statuses
            assert all(s == 400 for s in statuses[:-1]), statuses
            assert svc.executor.stats.breaker_opens >= 1
            assert svc.executor.stats.breaker_host_served >= 1

        run(ServerOptions(), fn)

    def test_host_spill_site_falls_back_to_device(self):
        """A faulted spill must not fail the request: it books a spill
        error and rides the device path."""
        failpoints.activate("host.spill=error")

        async def fn(client, _):
            svc = client.app["service"]
            res = await client.post("/resize?width=100",
                                    data=fixture_bytes("imaginary.jpg"))
            assert res.status == 200
            assert res.headers.get("X-Imaginary-Backend") == "device"
            assert svc.executor.stats.spill_errors >= 1

        run(ServerOptions(force_host=True), fn)

    def test_codec_encode_site(self):
        failpoints.activate("codec.encode=error")

        async def fn(client, _):
            res = await client.post("/resize?width=100",
                                    data=fixture_bytes("imaginary.jpg"))
            assert res.status == 400

        run(ServerOptions(), fn)

    def test_cache_get_site_degrades_to_miss(self):
        """A failing cache tier costs latency, never availability: both
        the cold and would-be-hot request serve 200."""
        failpoints.activate("cache.get=error")

        async def fn(client, _):
            for _ in range(2):
                res = await client.post("/resize?width=100",
                                        data=multipart_jpg())
                assert res.status == 200
            assert failpoints.snapshot()["sites"]["cache.get"]["fired"] >= 2

        run(ServerOptions(cache_result_mb=8.0, cache_frame_mb=8.0), fn)


class TestChaosScenarios:
    def test_flaky_origin_retries_converge(self):
        """source.fetch=error(0.5) with a retry budget: the overwhelming
        majority of requests converge to 2xx (per-request failure odds
        with 4 retries: 0.5^5 ~= 3%)."""
        from aiohttp import web as aioweb

        async def origin(request):
            return aioweb.Response(body=fixture_bytes("imaginary.jpg"),
                                   content_type="image/jpeg")

        failpoints.activate("source.fetch=error(0.5)")

        async def fn(client, origin_url):
            statuses = []
            for _ in range(20):
                res = await client.get(
                    f"/resize?width=100&url={origin_url}/i.jpg")
                statuses.append(res.status)
            ok = sum(1 for s in statuses if s == 200)
            assert ok >= 15, statuses
            assert all(s in (200, 502) for s in statuses), statuses

        run(ServerOptions(enable_url_source=True, source_retries=4),
            fn, origin_handler=origin)

    def test_dead_origin_502_within_budget(self):
        """error(1.0): retries exhaust, the request maps to 502 (not the
        old blanket 400), inside the request deadline."""
        from aiohttp import web as aioweb

        async def origin(request):
            return aioweb.Response(body=fixture_bytes("imaginary.jpg"),
                                   content_type="image/jpeg")

        failpoints.activate("source.fetch=error")

        async def fn(client, origin_url):
            t0 = time.monotonic()
            res = await client.get(f"/resize?width=100&url={origin_url}/i.jpg")
            elapsed = time.monotonic() - t0
            assert res.status == 502
            body = await res.json()
            assert "injected error" in body["message"]
            assert elapsed < 2.0

        run(ServerOptions(enable_url_source=True, request_timeout_s=2.0),
            fn, origin_handler=origin)

    def test_origin_timeout_maps_to_504(self):
        failpoints.activate("source.fetch=timeout(10ms)")

        from aiohttp import web as aioweb

        async def origin(request):
            return aioweb.Response(body=b"unreached")

        async def fn(client, origin_url):
            res = await client.get(f"/resize?width=100&url={origin_url}/i.jpg")
            assert res.status == 504
            body = await res.json()
            assert "timed out" in body["message"]

        run(ServerOptions(enable_url_source=True, source_retries=1),
            fn, origin_handler=origin)

    def test_fault_mid_coalesce_fans_out_to_all_waiters(self):
        """N concurrent identical requests coalesce onto one run; an
        injected decode fault must fan the SAME error out to every waiter
        — no hangs, no stragglers, and the group ledger drains."""
        failpoints.activate("codec.decode=error")

        async def fn(client, _):
            svc = client.app["service"]
            blob = fixture_bytes("imaginary.jpg")

            async def one():
                res = await client.post("/resize?width=100", data=blob)
                return res.status, (await res.json())["message"]

            results = await asyncio.gather(*[one() for _ in range(8)])
            assert all(status == 400 for status, _ in results), results
            assert all("injected error" in msg for _, msg in results)
            # the coalescer's group map drained (no leaked groups)
            assert svc.caches.flight.inflight() == 0

        run(ServerOptions(cache_coalesce=True), fn)

    def test_breaker_invariants_under_concurrent_chaos(self):
        """Concurrent traffic against a dead device: every request
        resolves (400 until the breaker opens, then host-served 200),
        nothing hangs, and the gate/ledger counters return to rest."""
        failpoints.activate("device.execute=error")

        async def fn(client, _):
            svc = client.app["service"]
            blob = fixture_bytes("imaginary.jpg")

            async def one(i):
                res = await client.post(f"/resize?width=10{i % 3}", data=blob)
                return res.status

            statuses = await asyncio.gather(*[one(i) for i in range(12)])
            assert all(s in (200, 400) for s in statuses), statuses
            assert 200 in statuses  # breaker failover engaged
            # ledgers at rest once traffic stops
            for _ in range(50):
                with svc._inflight_lock:
                    if svc._inflight == 0:
                        break
                await asyncio.sleep(0.02)
            with svc._inflight_lock:
                assert svc._inflight == 0
            assert svc.executor.estimated_wait_ms() == pytest.approx(0.0, abs=1e-6)

        run(ServerOptions(), fn)


class TestDebugzControlSurface:
    def test_get_put_round_trip(self):
        async def fn(client, _):
            # arm at runtime
            res = await client.put("/debugz/failpoints",
                                   data="codec.decode=error")
            assert res.status == 200
            body = await res.json()
            assert body["enabled"] and "codec.decode" in body["sites"]

            bad = await client.post("/resize?width=100",
                                    data=fixture_bytes("imaginary.jpg"))
            assert bad.status == 400

            # observe counters, then disarm with an empty PUT
            res = await client.get("/debugz/failpoints")
            snap = await res.json()
            assert snap["sites"]["codec.decode"]["fired"] >= 1

            res = await client.put("/debugz/failpoints", data="")
            assert (await res.json())["enabled"] is False

            ok = await client.post("/resize?width=100",
                                   data=fixture_bytes("imaginary.jpg"))
            assert ok.status == 200

        run(ServerOptions(enable_debug=True), fn)

    def test_bad_spec_rejected_400(self):
        async def fn(client, _):
            res = await client.put("/debugz/failpoints", data="nope=error")
            assert res.status == 400
            assert "unknown failpoint site" in (await res.json())["error"]

        run(ServerOptions(enable_debug=True), fn)

    def test_gated_behind_enable_debug(self):
        async def fn(client, _):
            res = await client.get("/debugz/failpoints")
            assert res.status == 404
            res = await client.put("/debugz/failpoints", data="codec.decode=error")
            assert res.status == 405  # PUT never even validates when gated

        run(ServerOptions(), fn)

    def test_env_arming_through_create_app(self, monkeypatch):
        monkeypatch.setenv(failpoints.ENV_VAR, "codec.encode=error(0.0)")

        async def fn(client, _):
            assert failpoints.snapshot()["enabled"]
            assert "codec.encode" in failpoints.snapshot()["sites"]

        run(ServerOptions(), fn)

    def test_failpoints_in_debugz_payload(self):
        failpoints.activate("codec.decode=error(0.0)")

        async def fn(client, _):
            res = await client.get("/debugz")
            body = await res.json()
            assert body["failpoints"]["enabled"]
            assert "codec.decode" in body["failpoints"]["sites"]

        run(ServerOptions(enable_debug=True), fn)
