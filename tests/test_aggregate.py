"""Fleet metrics aggregation (imaginary_tpu/obs/aggregate.py).

The ISSUE 13 merged-exposition contract: two synthetic worker snapshots
(one mid-respawn with reset counters) merge to monotonic fleet totals
that pass the PR 3 strict exposition parser; gauge families follow the
mergeable-vs-per-worker discipline (summing the shared shm's slot gauge
over N workers would N-x double-count); /fleetz degrades gracefully
(partial data + `stale` flag) when a worker never answers the scrape;
and the FleetAdmin HTTP server serves both views end to end.

Everything here is supervisor-side and stdlib-only — no jax, no
aiohttp, no live fleet (tests/test_workers.py covers the real
2-worker subprocess path).
"""

import http.client
import itertools
import json
import threading

import pytest

from imaginary_tpu.obs.aggregate import (
    Aggregator,
    FleetAdmin,
    build_fleetz,
    merge_mode,
    parse_exposition,
    scrape_fleet,
)
from tests.test_obs import check_histograms, parse_exposition_strict


def worker_exposition(worker: int, epoch: int, requests: float,
                      bucket_01: float, threads: float = 7,
                      fleet_slots: float = 128.0) -> str:
    """A minimal but representative worker /metrics body: identity
    gauges, a RED counter, a histogram, a summable gauge, and the
    shared-shm slot gauge every worker reports identically."""
    dur_sum = requests * 0.05
    return (
        "# HELP imaginary_tpu_worker Worker index of the serving process.\n"
        "# TYPE imaginary_tpu_worker gauge\n"
        f"imaginary_tpu_worker {worker}\n"
        "# HELP imaginary_tpu_epoch Supervisor-minted fencing epoch.\n"
        "# TYPE imaginary_tpu_epoch gauge\n"
        f"imaginary_tpu_epoch {epoch}\n"
        "# HELP imaginary_tpu_requests_total Requests by route and class.\n"
        "# TYPE imaginary_tpu_requests_total counter\n"
        f'imaginary_tpu_requests_total{{route="resize",code="2xx"}} '
        f"{requests}\n"
        "# HELP imaginary_tpu_request_duration_seconds End-to-end latency.\n"
        "# TYPE imaginary_tpu_request_duration_seconds histogram\n"
        f'imaginary_tpu_request_duration_seconds_bucket{{le="0.1"}} '
        f"{bucket_01}\n"
        f'imaginary_tpu_request_duration_seconds_bucket{{le="+Inf"}} '
        f"{requests}\n"
        f"imaginary_tpu_request_duration_seconds_sum {dur_sum}\n"
        f"imaginary_tpu_request_duration_seconds_count {requests}\n"
        "# HELP imaginary_tpu_threads Live threads in this process.\n"
        "# TYPE imaginary_tpu_threads gauge\n"
        f"imaginary_tpu_threads {threads}\n"
        "# HELP imaginary_tpu_fleet_slots Slots in the shared shm cache.\n"
        "# TYPE imaginary_tpu_fleet_slots gauge\n"
        f"imaginary_tpu_fleet_slots {fleet_slots}\n"
        "# HELP imaginary_tpu_rss_mb Resident set size.\n"
        "# TYPE imaginary_tpu_rss_mb gauge\n"
        f"imaginary_tpu_rss_mb {100 + worker}\n"
    )


def health_body(worker: int, epoch: int) -> str:
    return json.dumps({"worker": worker, "epoch": epoch,
                       "uptime": 12.5, "backend": "cpu"})


class TestParseExposition:
    def test_histogram_samples_fold_into_base_family(self):
        fams = parse_exposition(worker_exposition(0, 1, 10, 8))
        hist = fams["imaginary_tpu_request_duration_seconds"]
        assert hist.mtype == "histogram"
        sample_names = {name for name, _ in hist.samples}
        assert sample_names == {
            "imaginary_tpu_request_duration_seconds_bucket",
            "imaginary_tpu_request_duration_seconds_sum",
            "imaginary_tpu_request_duration_seconds_count",
        }

    def test_labels_and_values(self):
        fams = parse_exposition(worker_exposition(0, 1, 10, 8))
        red = fams["imaginary_tpu_requests_total"]
        ((name, labels),) = [k for k in red.samples]
        assert name == "imaginary_tpu_requests_total"
        assert dict(labels) == {"route": "resize", "code": "2xx"}
        assert red.samples[(name, labels)] == 10.0

    def test_tolerates_openmetrics_exemplar_clause(self):
        text = (
            "# TYPE imaginary_tpu_request_duration_seconds histogram\n"
            'imaginary_tpu_request_duration_seconds_bucket{le="0.1"} 8'
            ' # {trace_id="abc",request_id="rid"} 0.07\n'
        )
        fams = parse_exposition(text)
        hist = fams["imaginary_tpu_request_duration_seconds"]
        assert list(hist.samples.values()) == [8.0]

    def test_label_value_containing_brace(self):
        # Prometheus only requires escaping '"', '\' and newline in a
        # label value, so a literal '}' (think templated route labels)
        # is legal and must not truncate the label block
        text = (
            "# TYPE imaginary_tpu_requests_total counter\n"
            'imaginary_tpu_requests_total'
            '{route="/v1/{spec}/resize",code="2xx"} 3\n'
        )
        fams = parse_exposition(text)
        red = fams["imaginary_tpu_requests_total"]
        ((name, labels),) = list(red.samples)
        assert dict(labels) == {"route": "/v1/{spec}/resize", "code": "2xx"}
        assert red.samples[(name, labels)] == 3.0


class TestMergeMode:
    def test_counters_and_histograms_sum(self):
        assert merge_mode("imaginary_tpu_requests_total", "counter") == "sum"
        assert merge_mode(
            "imaginary_tpu_request_duration_seconds", "histogram") == "sum"

    def test_shared_shm_gauges_never_sum(self):
        # every worker reports the SAME shm file: summing double-counts
        assert merge_mode("imaginary_tpu_fleet_slots", "gauge") == "per_worker"
        assert merge_mode("imaginary_tpu_fleet_used_bytes",
                          "gauge") == "per_worker"

    def test_per_process_quantities_sum_only_when_allowlisted(self):
        assert merge_mode("imaginary_tpu_executor_queue_depth",
                          "gauge") == "sum"
        assert merge_mode("imaginary_tpu_threads", "gauge") == "sum"
        # categorical / identity / per-process state: labeled, not summed
        assert merge_mode("imaginary_tpu_rss_mb", "gauge") == "per_worker"
        assert merge_mode("imaginary_tpu_pressure_state",
                          "gauge") == "per_worker"


class TestAggregatorMonotonicity:
    def test_two_workers_sum(self):
        agg = Aggregator()
        agg.observe(0, 1, parse_exposition(worker_exposition(0, 1, 100, 80)))
        agg.observe(1, 2, parse_exposition(worker_exposition(1, 2, 40, 30)))
        types, samples = parse_exposition_strict(agg.render())
        red = {tuple(sorted(labels.items())): v for n, labels, v in samples
               if n == "imaginary_tpu_requests_total"}
        assert list(red.values()) == [140.0]

    def test_respawn_reset_never_goes_backwards(self):
        # worker 1 crashes at 40 requests and respawns (epoch 2 -> 5)
        # with counters back at zero; the merged total must never dip
        agg = Aggregator()
        agg.observe(0, 1, parse_exposition(worker_exposition(0, 1, 100, 80)))
        agg.observe(1, 2, parse_exposition(worker_exposition(1, 2, 40, 30)))

        def fleet_total():
            _, samples = parse_exposition_strict(agg.render())
            return next(v for n, _l, v in samples
                        if n == "imaginary_tpu_requests_total")

        assert fleet_total() == 140.0
        agg.observe(1, 5, parse_exposition(worker_exposition(1, 5, 0, 0)))
        assert fleet_total() == 140.0  # dead epoch folded into the base
        agg.observe(1, 5, parse_exposition(worker_exposition(1, 5, 7, 5)))
        assert fleet_total() == 147.0
        # histogram counts ride the same correction
        _, samples = parse_exposition_strict(agg.render())
        count = next(v for n, _l, v in samples
                     if n == "imaginary_tpu_request_duration_seconds_count")
        assert count == 147.0

    def test_same_epoch_regression_clamped(self):
        agg = Aggregator()
        agg.observe(0, 1, parse_exposition(worker_exposition(0, 1, 50, 40)))
        agg.observe(0, 1, parse_exposition(worker_exposition(0, 1, 44, 40)))
        _, samples = parse_exposition_strict(agg.render())
        total = next(v for n, _l, v in samples
                     if n == "imaginary_tpu_requests_total")
        assert total == 50.0

    def test_older_epoch_scrape_ignored(self):
        # a deposed zombie's last gasp racing its replacement
        agg = Aggregator()
        agg.observe(0, 3, parse_exposition(worker_exposition(0, 3, 10, 8)))
        agg.observe(0, 2, parse_exposition(
            worker_exposition(0, 2, 9999, 9999)))
        _, samples = parse_exposition_strict(agg.render())
        total = next(v for n, _l, v in samples
                     if n == "imaginary_tpu_requests_total")
        assert total == 10.0


class TestSummableGaugeSnapshots:
    """Summable gauges are latest-snapshot sums, never reset-corrected:
    the counter machinery's max() clamp and base folding would pin a
    draining queue at its high-water mark and inflate fleet totals on
    every respawn."""

    def _gauge(self, agg, name="imaginary_tpu_threads"):
        _, samples = parse_exposition_strict(agg.render())
        return next(v for n, _l, v in samples if n == name)

    def test_gauge_decrease_tracks_snapshot(self):
        agg = Aggregator()
        agg.observe(0, 1, parse_exposition(
            worker_exposition(0, 1, 10, 8, threads=9)))
        agg.observe(1, 2, parse_exposition(
            worker_exposition(1, 2, 10, 8, threads=7)))
        assert self._gauge(agg) == 16.0
        # worker 0's pool shrinks: the fleet total must follow DOWN
        agg.observe(0, 1, parse_exposition(
            worker_exposition(0, 1, 12, 9, threads=3)))
        assert self._gauge(agg) == 10.0

    def test_gauge_not_inflated_across_respawn(self):
        agg = Aggregator()
        agg.observe(0, 1, parse_exposition(
            worker_exposition(0, 1, 10, 8, threads=9)))
        # respawn (epoch 1 -> 4): the new incarnation's gauge REPLACES
        # the dead one's — no permanent base from the old value
        agg.observe(0, 4, parse_exposition(
            worker_exposition(0, 4, 0, 0, threads=5)))
        assert self._gauge(agg) == 5.0
        # ...while the counter DID fold the dead incarnation's total
        _, samples = parse_exposition_strict(agg.render())
        total = next(v for n, _l, v in samples
                     if n == "imaginary_tpu_requests_total")
        assert total == 10.0

    def test_per_worker_view_serves_snapshots_too(self):
        agg = Aggregator()
        agg.observe(0, 1, parse_exposition(
            worker_exposition(0, 1, 10, 8, threads=9)))
        agg.observe(0, 1, parse_exposition(
            worker_exposition(0, 1, 11, 9, threads=2)))
        _, samples = parse_exposition_strict(agg.render(per_worker=True))
        threads = {labels["worker"]: v for n, labels, v in samples
                   if n == "imaginary_tpu_threads"}
        assert threads == {"0": 2.0}


class TestPrune:
    def _agg(self):
        agg = Aggregator()
        agg.observe(0, 1, parse_exposition(worker_exposition(0, 1, 100, 80)))
        agg.observe(1, 2, parse_exposition(worker_exposition(1, 2, 40, 30)))
        return agg

    def test_departed_worker_state_evicted(self):
        agg = self._agg()
        agg.prune({0})
        assert agg.workers_seen() == {0: 1}
        _, samples = parse_exposition_strict(agg.render())
        # per-worker series for the departed index are gone
        assert {labels["worker"] for n, labels, _v in samples
                if n == "imaginary_tpu_rss_mb"} == {"0"}
        # its summable-gauge contribution drops out of the fleet total
        threads = next(v for n, _l, v in samples
                       if n == "imaginary_tpu_threads")
        assert threads == 7.0
        # but counter totals stay monotonic: the retired index's final
        # value folds into a per-series base
        total = next(v for n, _l, v in samples
                     if n == "imaginary_tpu_requests_total")
        assert total == 140.0
        count = next(v for n, _l, v in samples
                     if n == "imaginary_tpu_request_duration_seconds_count")
        assert count == 140.0

    def test_retired_base_survives_later_observes(self):
        agg = self._agg()
        agg.prune({0})
        agg.observe(0, 1, parse_exposition(worker_exposition(0, 1, 107, 85)))
        _, samples = parse_exposition_strict(agg.render())
        total = next(v for n, _l, v in samples
                     if n == "imaginary_tpu_requests_total")
        assert total == 147.0

    def test_prune_noop_when_all_tracked(self):
        agg = self._agg()
        agg.prune({0, 1})
        _, samples = parse_exposition_strict(agg.render())
        total = next(v for n, _l, v in samples
                     if n == "imaginary_tpu_requests_total")
        assert total == 140.0
        assert agg.workers_seen() == {0: 1, 1: 2}


class TestMergedRender:
    def _agg(self):
        agg = Aggregator()
        agg.observe(0, 1, parse_exposition(worker_exposition(0, 1, 100, 80)))
        agg.observe(1, 2, parse_exposition(worker_exposition(1, 2, 40, 30)))
        return agg

    def test_strict_parse_and_histogram_consistency(self):
        types, samples = parse_exposition_strict(self._agg().render())
        check_histograms(types, samples)
        assert types["imaginary_tpu_requests_total"] == "counter"
        assert types["imaginary_tpu_request_duration_seconds"] == "histogram"

    def test_gauge_discipline_in_merged_view(self):
        _, samples = parse_exposition_strict(self._agg().render())
        by_name: dict = {}
        for n, labels, v in samples:
            by_name.setdefault(n, []).append((labels, v))
        # allowlisted gauge summed into one series
        ((labels, v),) = by_name["imaginary_tpu_threads"]
        assert "worker" not in labels and v == 14.0
        # shared-shm gauge split per worker, never summed
        slots = by_name["imaginary_tpu_fleet_slots"]
        assert sorted(labels["worker"] for labels, _ in slots) == ["0", "1"]
        assert all(v == 128.0 for _, v in slots)
        # identity gauge dropped from the merged view entirely
        assert "imaginary_tpu_worker" not in by_name
        # per-process gauge labeled by worker
        rss = {labels["worker"]: v
               for labels, v in by_name["imaginary_tpu_rss_mb"]}
        assert rss == {"0": 100.0, "1": 101.0}

    def test_per_worker_debug_view(self):
        text = self._agg().render(per_worker=True)
        types, samples = parse_exposition_strict(text)
        red = [(labels, v) for n, labels, v in samples
               if n == "imaginary_tpu_requests_total"]
        assert {labels["worker"]: v for labels, v in red} \
            == {"0": 100.0, "1": 40.0}

    def test_extra_gauges_appended(self):
        text = self._agg().render(extra_gauges=[
            ("imaginary_tpu_fleet_admin_workers", "tracked workers", 2)])
        types, samples = parse_exposition_strict(text)
        assert types["imaginary_tpu_fleet_admin_workers"] == "gauge"
        assert any(n == "imaginary_tpu_fleet_admin_workers" and v == 2.0
                   for n, _l, v in samples)


# --- shared-port scraping -----------------------------------------------------


def round_robin_fetch(bodies_by_kind):
    """fetch(url, timeout) that cycles each URL kind through a list of
    bodies — models the kernel's SO_REUSEPORT pick landing on successive
    workers. A body of None raises TimeoutError (worker not answering)."""
    counters = {kind: itertools.cycle(bodies)
                for kind, bodies in bodies_by_kind.items()}
    lock = threading.Lock()

    def fetch(url, timeout):
        kind = "metrics" if "/metrics" in url else "health"
        with lock:
            body = next(counters[kind])
        if body is None:
            raise TimeoutError("worker did not answer")
        return body

    return fetch


class TestScrapeFleet:
    def test_full_coverage(self):
        fetch = round_robin_fetch({
            "metrics": [worker_exposition(0, 1, 10, 8),
                        worker_exposition(1, 1, 20, 15)],
            "health": [health_body(0, 1), health_body(1, 1)],
        })
        metrics_by, health_by, missed = scrape_fleet(
            "http://x/metrics", "http://x/health", {0, 1},
            deadline_s=2.0, fetch=fetch)
        assert missed == set()
        assert set(metrics_by) == {0, 1} and set(health_by) == {0, 1}
        assert metrics_by[0][0] == 1  # epoch rode along
        assert health_by[1]["worker"] == 1

    def test_unresponsive_worker_reported_missed(self):
        # worker 1 never answers: every sample lands on worker 0 or
        # times out; the scrape must return partial data, not hang or 500
        fetch = round_robin_fetch({
            "metrics": [worker_exposition(0, 1, 10, 8), None],
            "health": [health_body(0, 1), None],
        })
        metrics_by, health_by, missed = scrape_fleet(
            "http://x/metrics", "http://x/health", {0, 1},
            deadline_s=0.3, per_request_timeout=0.05, fetch=fetch)
        assert missed == {1}
        assert set(metrics_by) == {0} and set(health_by) == {0}

    def test_higher_epoch_wins_within_one_scrape(self):
        # zombie + replacement both answering during a roll: keep the new
        fetch = round_robin_fetch({
            "metrics": [worker_exposition(0, 4, 3, 2),
                        worker_exposition(0, 3, 900, 900)],
            "health": [health_body(0, 4), health_body(0, 3)],
        })
        metrics_by, health_by, missed = scrape_fleet(
            "http://x/metrics", "http://x/health", {0},
            deadline_s=0.3, fetch=fetch)
        assert metrics_by[0][0] == 4
        assert health_by[0]["epoch"] == 4


class TestFleetz:
    def test_stale_flag_on_missed_worker(self):
        view = {
            0: {"pid": 11, "alive": True, "epoch": 1, "restarts": 0},
            1: {"pid": 12, "alive": True, "epoch": 3, "restarts": 2},
        }
        payload = build_fleetz(view, {0: json.loads(health_body(0, 1))},
                               missed={1}, now=123.0)
        w = payload["workers"]
        assert w["0"]["stale"] is False
        assert w["0"]["health"]["backend"] == "cpu"
        # the missed worker still appears with supervisor truth
        assert w["1"]["stale"] is True and w["1"]["health"] is None
        assert w["1"]["pid"] == 12 and w["1"]["restarts"] == 2
        assert payload["missed"] == [1]
        assert payload["scraped"] == [0]


# --- the admin HTTP server, end to end ----------------------------------------


@pytest.fixture
def admin():
    fetch = round_robin_fetch({
        "metrics": [worker_exposition(0, 1, 100, 80),
                    worker_exposition(1, 2, 40, 30)],
        "health": [health_body(0, 1), health_body(1, 2)],
    })

    def view():
        return {0: {"pid": 11, "alive": True, "epoch": 1, "restarts": 0},
                1: {"pid": 12, "alive": True, "epoch": 2, "restarts": 1}}

    srv = FleetAdmin(0, "http://shared/metrics", "http://shared/health",
                     view, scrape_deadline_s=1.0, fetch=fetch).start()
    try:
        yield srv
    finally:
        srv.close()


def _get(port: int, path: str):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode("utf-8")
    finally:
        conn.close()


class TestFleetAdminHTTP:
    def test_merged_metrics_strict_and_summed(self, admin):
        status, text = _get(admin.port, "/metrics")
        assert status == 200
        types, samples = parse_exposition_strict(text)
        check_histograms(types, samples)
        total = next(v for n, _l, v in samples
                     if n == "imaginary_tpu_requests_total")
        assert total == 140.0
        # the synthetic supervisor gauges ride along
        assert any(n == "imaginary_tpu_fleet_admin_workers" and v == 2.0
                   for n, _l, v in samples)
        assert any(n == "imaginary_tpu_fleet_admin_workers_unscraped"
                   and v == 0.0 for n, _l, v in samples)

    def test_per_worker_query(self, admin):
        status, text = _get(admin.port, "/metrics?per_worker=1")
        assert status == 200
        _, samples = parse_exposition_strict(text)
        red = {labels["worker"]: v for n, labels, v in samples
               if n == "imaginary_tpu_requests_total"}
        assert red == {"0": 100.0, "1": 40.0}

    def test_fleetz_shape(self, admin):
        status, text = _get(admin.port, "/fleetz")
        assert status == 200
        payload = json.loads(text)
        assert set(payload["workers"]) == {"0", "1"}
        assert payload["workers"]["1"]["restarts"] == 1
        assert payload["workers"]["1"]["health"]["epoch"] == 2
        assert payload["missed"] == []

    def test_unknown_path_404(self, admin):
        status, _ = _get(admin.port, "/nope")
        assert status == 404

    def test_scaled_down_worker_evicted_but_totals_hold(self):
        # the supervisor stops tracking index 1 between two admin
        # requests; its zombie keeps answering the shared port. The
        # merged view must drop its gauges (no stale series forever)
        # without regressing fleet counter totals — and without
        # re-folding the zombie's answers into the base every scrape.
        fetch = round_robin_fetch({
            "metrics": [worker_exposition(0, 1, 100, 80),
                        worker_exposition(1, 2, 40, 30)],
            "health": [health_body(0, 1), health_body(1, 2)],
        })
        tracked = {0: {"pid": 11, "alive": True, "epoch": 1, "restarts": 0},
                   1: {"pid": 12, "alive": True, "epoch": 2, "restarts": 1}}

        srv = FleetAdmin(0, "http://shared/metrics", "http://shared/health",
                         lambda: dict(tracked), scrape_deadline_s=1.0,
                         fetch=fetch).start()
        try:
            _, text = _get(srv.port, "/metrics")
            _, samples = parse_exposition_strict(text)
            assert next(v for n, _l, v in samples
                        if n == "imaginary_tpu_requests_total") == 140.0
            del tracked[1]
            for _ in range(2):  # two scrapes: retired base must not grow
                _, text = _get(srv.port, "/metrics")
            _, samples = parse_exposition_strict(text)
            assert {labels["worker"] for n, labels, _v in samples
                    if n == "imaginary_tpu_rss_mb"} == {"0"}
            assert next(v for n, _l, v in samples
                        if n == "imaginary_tpu_threads") == 7.0
            assert next(v for n, _l, v in samples
                        if n == "imaginary_tpu_requests_total") == 140.0
        finally:
            srv.close()

    def test_totals_monotonic_across_admin_requests(self, admin):
        # the persistent Aggregator means a second scrape that catches a
        # freshly-respawned worker cannot regress the merged totals
        _, text1 = _get(admin.port, "/metrics")
        _, samples1 = parse_exposition_strict(text1)
        _, text2 = _get(admin.port, "/metrics")
        _, samples2 = parse_exposition_strict(text2)
        t1 = next(v for n, _l, v in samples1
                  if n == "imaginary_tpu_requests_total")
        t2 = next(v for n, _l, v in samples2
                  if n == "imaginary_tpu_requests_total")
        assert t2 >= t1
