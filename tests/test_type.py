"""Format/MIME mapping tests (modeled on type_test.go)."""

import pytest

from imaginary_tpu.imgtype import (
    ImageType,
    determine_image_type,
    extract_image_type_from_mime,
    get_image_mime_type,
    image_type,
    is_image_mime_type_supported,
)


@pytest.mark.parametrize(
    "mime,expected",
    [
        ("image/jpeg", "jpeg"),
        ("/jpeg", "jpeg"),
        ("image/png", "png"),
        ("image/webp", "webp"),
        ("IMAGE/JPEG", "jpeg"),
        ("png", ""),
        ("multipart/form-data; encoding=utf-8", "form-data"),
        ("application/json", "json"),
        ("image/svg+xml", "svg"),
        ("image/svg+xml; charset=utf-8", "svg"),
        ("image/svg", "svg"),
        ("xml", ""),
        ("", ""),
    ],
)
def test_extract_image_type_from_mime(mime, expected):
    assert extract_image_type_from_mime(mime) == expected


@pytest.mark.parametrize(
    "mime,expected",
    [
        ("image/jpeg", True),
        ("image/png", True),
        ("image/webp", True),
        ("IMAGE/JPEG", True),
        ("image/svg+xml", True),
        ("image/svg+xml; charset=utf-8", True),
        ("image/tiff", True),
        ("application/json", False),
        ("text/plain", False),
        ("blah", False),
    ],
)
def test_is_image_mime_type_supported(mime, expected):
    assert is_image_mime_type_supported(mime) is expected


@pytest.mark.parametrize(
    "name,expected",
    [
        ("jpeg", ImageType.JPEG),
        ("jpg", ImageType.JPEG),
        ("JPG", ImageType.JPEG),
        ("png", ImageType.PNG),
        ("webp", ImageType.WEBP),
        ("tiff", ImageType.TIFF),
        ("gif", ImageType.GIF),
        ("svg", ImageType.SVG),
        ("pdf", ImageType.PDF),
        ("bogus", ImageType.UNKNOWN),
    ],
)
def test_image_type(name, expected):
    assert image_type(name) is expected


def test_get_image_mime_type():
    assert get_image_mime_type(ImageType.PNG) == "image/png"
    assert get_image_mime_type(ImageType.WEBP) == "image/webp"
    assert get_image_mime_type(ImageType.SVG) == "image/svg+xml"
    # unknown falls back to jpeg (type.go:46-60)
    assert get_image_mime_type(ImageType.UNKNOWN) == "image/jpeg"
    assert get_image_mime_type(ImageType.JPEG) == "image/jpeg"


def test_determine_image_type_magic():
    assert determine_image_type(b"\xff\xd8\xff\xe0" + b"\x00" * 16) is ImageType.JPEG
    assert determine_image_type(b"\x89PNG\r\n\x1a\n" + b"\x00" * 16) is ImageType.PNG
    assert determine_image_type(b"RIFF\x00\x00\x00\x00WEBPVP8 ") is ImageType.WEBP
    assert determine_image_type(b"GIF89a" + b"\x00" * 16) is ImageType.GIF
    assert determine_image_type(b"II*\x00" + b"\x00" * 16) is ImageType.TIFF
    assert determine_image_type(b"%PDF-1.4") is ImageType.PDF
    assert determine_image_type(b"<svg xmlns='http://www.w3.org/2000/svg'/>") is ImageType.SVG
    assert determine_image_type(b"\x00\x00\x00 ftypavif") is ImageType.AVIF
    assert determine_image_type(b"\x00\x00\x00 ftypheic") is ImageType.HEIF
    assert determine_image_type(b"junk") is ImageType.UNKNOWN
    assert determine_image_type(b"") is ImageType.UNKNOWN
