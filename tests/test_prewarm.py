"""Compile-cache warming (prewarm.py): ladder + shrink-bucket coverage."""

import numpy as np

from imaginary_tpu.options import ImageOptions


def test_prewarm_ladder_and_shrink_bucket(monkeypatch):
    """Prewarm compiles every requested batch size, at the SHRUNK decode
    dims production serves (not the full source dims), deduped by
    (chain, bucket, batch)."""
    from imaginary_tpu import prewarm
    from imaginary_tpu.ops import chain as chain_mod
    from imaginary_tpu.ops.plan import choose_decode_shrink

    monkeypatch.setattr(
        prewarm, "_COMMON", [("resize", ImageOptions(width=24), (64, 96))]
    )
    before = chain_mod.cache_size()
    n = prewarm.prewarm_common_chains(batch_sizes=(1, 2), verbose=False)
    # both the full bucket (PNG/WebP traffic) and the shrink-on-load bucket
    # (JPEG traffic) are warmed, per batch size, deduped by (chain, bucket, b);
    # when the native raw codec is present the packed-YUV420 transport chain
    # warms alongside each RGB chain
    from imaginary_tpu import codecs

    shrink = choose_decode_shrink("resize", ImageOptions(width=24), 64, 96, 0, 3)
    expected_dims = {(64, 96), ((64 + shrink - 1) // shrink, (96 + shrink - 1) // shrink)}
    transports = 2 if codecs.yuv420_supported() else 1
    assert n == 2 * len(expected_dims) * transports
    assert chain_mod.cache_size() >= before  # programs landed in the cache


def test_prewarm_env_override(monkeypatch):
    from imaginary_tpu import prewarm
    from imaginary_tpu.ops.plan import choose_decode_shrink

    monkeypatch.setattr(
        prewarm, "_COMMON", [("resize", ImageOptions(width=16), (32, 48))]
    )
    from imaginary_tpu import codecs

    shrink = choose_decode_shrink("resize", ImageOptions(width=16), 32, 48, 0, 3)
    dims = {(32, 48), ((32 + shrink - 1) // shrink, (48 + shrink - 1) // shrink)}
    transports = 2 if codecs.yuv420_supported() else 1
    monkeypatch.setenv("IMAGINARY_TPU_PREWARM_BATCHES", "1")
    assert prewarm.prewarm_common_chains(verbose=False) == len(dims) * transports


def test_prewarm_bad_env_degrades(monkeypatch):
    """Malformed batch env must not kill the server before bind."""
    from imaginary_tpu import prewarm

    monkeypatch.setattr(
        prewarm, "_COMMON", [("resize", ImageOptions(width=16), (32, 48))]
    )
    monkeypatch.setenv("IMAGINARY_TPU_PREWARM_BATCHES", "1 2;bogus")
    assert prewarm.prewarm_common_chains(verbose=False) >= 1  # fell back to ladder


def test_persistent_cache_degrades_on_unwritable(monkeypatch):
    """chmod can't stop root, so simulate the read-only fs directly."""
    from imaginary_tpu import prewarm

    def boom(*a, **k):
        raise PermissionError("read-only file system")

    monkeypatch.setattr(prewarm.os, "makedirs", boom)
    assert prewarm.enable_persistent_cache("/ro/cache") == ""  # degrade, not die
