"""Compile-cache warming (prewarm.py): ladder + shrink-bucket coverage."""

import numpy as np

from imaginary_tpu.options import ImageOptions


def test_prewarm_ladder_and_shrink_bucket(monkeypatch):
    """Prewarm compiles every requested batch size, at the SHRUNK decode
    dims production serves (not the full source dims), deduped by
    (chain, bucket, batch)."""
    from imaginary_tpu import prewarm
    from imaginary_tpu.ops import chain as chain_mod
    from imaginary_tpu.ops.plan import choose_decode_shrink

    monkeypatch.setattr(
        prewarm, "_COMMON", [("resize", ImageOptions(width=24), (64, 96))]
    )
    before = chain_mod.cache_size()
    n = prewarm.prewarm_common_chains(batch_sizes=(1, 2), verbose=False)
    # both the full bucket (PNG/WebP traffic) and the shrink-on-load bucket
    # (JPEG traffic) are warmed, per batch size, deduped by (chain, bucket, b);
    # when the native raw codec is present the packed-YUV420 transport chain
    # warms alongside each RGB chain
    from imaginary_tpu import codecs

    shrink = choose_decode_shrink("resize", ImageOptions(width=24), 64, 96, 0, 3)
    expected_dims = {(64, 96), ((64 + shrink - 1) // shrink, (96 + shrink - 1) // shrink)}
    transports = 2 if codecs.yuv420_supported() else 1
    assert n == 2 * len(expected_dims) * transports
    assert chain_mod.cache_size() >= before  # programs landed in the cache


def test_prewarm_env_override(monkeypatch):
    from imaginary_tpu import prewarm
    from imaginary_tpu.ops.plan import choose_decode_shrink

    monkeypatch.setattr(
        prewarm, "_COMMON", [("resize", ImageOptions(width=16), (32, 48))]
    )
    from imaginary_tpu import codecs

    shrink = choose_decode_shrink("resize", ImageOptions(width=16), 32, 48, 0, 3)
    dims = {(32, 48), ((32 + shrink - 1) // shrink, (48 + shrink - 1) // shrink)}
    transports = 2 if codecs.yuv420_supported() else 1
    monkeypatch.setenv("IMAGINARY_TPU_PREWARM_BATCHES", "1")
    assert prewarm.prewarm_common_chains(verbose=False) == len(dims) * transports


def test_prewarm_bad_env_degrades(monkeypatch):
    """Malformed batch env must not kill the server before bind."""
    from imaginary_tpu import prewarm

    monkeypatch.setattr(
        prewarm, "_COMMON", [("resize", ImageOptions(width=16), (32, 48))]
    )
    monkeypatch.setenv("IMAGINARY_TPU_PREWARM_BATCHES", "1 2;bogus")
    assert prewarm.prewarm_common_chains(verbose=False) >= 1  # fell back to ladder


def test_seed_link_rate_consumed_by_new_executor(monkeypatch):
    """A prewarm-installed link seed prices the device for executors
    created afterwards: a host-executable item whose estimated device
    wait exceeds spill_factor x host cost spills on the FIRST request —
    no unpriced ride over a slow link (the r4 cold-start wart: a fresh
    server's first requests each ate a full drain the host path serves
    in ~10 ms)."""
    from imaginary_tpu.engine import executor as executor_mod
    from imaginary_tpu.engine.executor import Executor, ExecutorConfig
    from imaginary_tpu.ops.plan import plan_operation

    monkeypatch.setattr(executor_mod, "_LINK_SEED", None)
    executor_mod.seed_link_rate(500.0, 40.0)  # a dreadful link: 500 ms/MB
    ex = Executor(ExecutorConfig(host_spill=True))
    try:
        assert ex._device_ms_per_mb == 500.0
        assert ex._drain_floor_ms == 40.0
        arr = np.zeros((256, 384, 3), dtype=np.uint8)
        plan = plan_operation("resize", ImageOptions(width=64), 256, 384, 0, 3)
        out = ex.process(arr, plan, timeout=60)
        assert out.shape[0] > 0
        assert ex.stats.spilled == 1  # priced link -> host, no device ride
        assert ex.stats.items == 0
    finally:
        ex.shutdown()


def test_seed_link_rate_solved_from_warm_drains(monkeypatch):
    """_seed_link_rate times a small and a large warm drain and installs a
    nonnegative (ms/MB, floor) pair."""
    from imaginary_tpu import prewarm
    from imaginary_tpu.engine import executor as executor_mod
    from imaginary_tpu.ops.plan import plan_operation

    monkeypatch.setattr(executor_mod, "_LINK_SEED", None)
    small = plan_operation("resize", ImageOptions(width=24), 64, 96, 0, 3)
    big = plan_operation("resize", ImageOptions(width=300), 512, 768, 0, 3)
    got = prewarm._seed_link_rate(
        [(small, None, 64, 96, 1), (big, None, 512, 768, 2)]
    )
    assert got is not None
    rate, floor = got
    assert rate >= 0.0 and floor >= 0.0
    assert executor_mod.link_seed() == (rate, floor)


def test_seed_link_rate_rejects_inverted_slope(monkeypatch):
    """Jitter can time the big drain FASTER than the small one; a 0.0
    seed would wedge the EWMA at 'link is free' forever (multiplicative
    clamps never leave 0), so no seed must install."""
    from imaginary_tpu import prewarm
    from imaginary_tpu.engine import executor as executor_mod
    from imaginary_tpu.ops.plan import plan_operation

    monkeypatch.setattr(executor_mod, "_LINK_SEED", None)

    def stalled_small(arrs, pls):
        # deterministic inversion: the SMALL drain (b=1) stalls, the big
        # one returns instantly -> negative slope, guaranteed
        import time as _t

        if len(arrs) == 1:
            _t.sleep(0.02)

    monkeypatch.setattr(prewarm.chain_mod, "run_batch", stalled_small)
    small = plan_operation("resize", ImageOptions(width=24), 64, 96, 0, 3)
    big = plan_operation("resize", ImageOptions(width=300), 512, 768, 0, 3)
    assert prewarm._seed_link_rate(
        [(small, None, 64, 96, 1), (big, None, 512, 768, 2)]
    ) is None  # inverted slope -> unseeded
    assert executor_mod.link_seed() is None


def test_zero_rate_seed_treated_as_unpriced(monkeypatch):
    """Even if seed_link_rate is handed a 0.0 rate directly, a new
    executor must treat the link as unpriced, not free."""
    from imaginary_tpu.engine import executor as executor_mod
    from imaginary_tpu.engine.executor import Executor, ExecutorConfig

    monkeypatch.setattr(executor_mod, "_LINK_SEED", None)
    executor_mod.seed_link_rate(0.0, 5.0)
    ex = Executor(ExecutorConfig(host_spill=True))
    try:
        assert ex._device_ms_per_mb is None
    finally:
        ex.shutdown()


def test_seed_link_rate_skips_degenerate_spread(monkeypatch):
    """Two near-identical wire sizes cannot fit a slope: no seed installed."""
    from imaginary_tpu import prewarm
    from imaginary_tpu.engine import executor as executor_mod
    from imaginary_tpu.ops.plan import plan_operation

    monkeypatch.setattr(executor_mod, "_LINK_SEED", None)
    pl = plan_operation("resize", ImageOptions(width=24), 64, 96, 0, 3)
    assert prewarm._seed_link_rate([(pl, None, 64, 96, 1)]) is None
    assert executor_mod.link_seed() is None


def test_persistent_cache_degrades_on_unwritable(monkeypatch):
    """chmod can't stop root, so simulate the read-only fs directly."""
    from imaginary_tpu import prewarm

    def boom(*a, **k):
        raise PermissionError("read-only file system")

    monkeypatch.setattr(prewarm.os, "makedirs", boom)
    assert prewarm.enable_persistent_cache("/ro/cache") == ""  # degrade, not die
