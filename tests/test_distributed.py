"""Multi-host initialization hook (SURVEY.md section 5.8; VERDICT r1 next #9).

jax.distributed.initialize is process-global and incompatible with the
already-initialized test backend, so the test drives the real code path in a
pinned subprocess: a 1-process "fleet" whose coordinator is itself — the
same call shape a TPU pod worker uses, minus auto-discovery.
"""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import jax
jax.config.update("jax_platforms", "cpu")
from imaginary_tpu.parallel.mesh import get_mesh, init_distributed

init_distributed(coordinator_address="127.0.0.1:{port}",
                 num_processes=1, process_id=0)
init_distributed()  # idempotent: second call must be a no-op
assert jax.process_count() == 1
mesh = get_mesh()
print("DIST_OK", jax.process_count(), dict(zip(mesh.axis_names, mesh.devices.shape)))
"""


def test_init_distributed_single_process_fleet():
    from tests.conftest import free_port

    port = free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-c", _CHILD.format(port=port)],
        capture_output=True, text=True, timeout=240, cwd=_ROOT, env=env,
    )
    if r.returncode != 0 and "distributed" in (r.stderr or "").lower():
        pytest.skip(f"jax.distributed unavailable here: {r.stderr[-200:]}")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "DIST_OK 1" in r.stdout


_WORKER = r"""
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
try:  # cross-process collectives on the CPU backend need gloo (jax 0.4.x
    # raises INVALID_ARGUMENT: "Multiprocess computations aren't
    # implemented on the CPU backend" without it; newer jaxlibs pick it
    # up automatically and may drop the option)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
from jax.sharding import PartitionSpec as P
try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # 0.4.x keeps it in jax.experimental
    from jax.experimental.shard_map import shard_map
from imaginary_tpu.parallel.mesh import batch_sharding, get_mesh, init_distributed

PID = {pid}
init_distributed(coordinator_address="127.0.0.1:{port}",
                 num_processes=2, process_id=PID)
assert jax.process_count() == 2, jax.process_count()
mesh = get_mesh()  # one GLOBAL mesh spanning both processes' devices

# 1) one collective across the fleet: psum over the batch axis rides the
#    cross-process (DCN-analogue) link
sharding = batch_sharding(mesh)
n_local = len(jax.local_devices())
n_global = mesh.devices.shape[0] * mesh.devices.shape[1]
x = jax.make_array_from_process_local_data(
    sharding, np.full((n_local,), float(PID + 1), np.float32), (n_global,))
f = jax.jit(shard_map(lambda v: jax.lax.psum(v, "batch"),
                      mesh=mesh, in_specs=P("batch"), out_specs=P()))
total = float(np.asarray(f(x).addressable_shards[0].data).ravel()[0])
expect = n_local * (1.0 + 2.0)  # each process contributes n_local shards
assert total == expect, (total, expect)
print("PSUM_OK", total == expect)

# 2) one dp-sharded chain step: each process contributes its local images;
#    the jitted chain runs once over the global mesh
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.ops import chain as chain_mod
from imaginary_tpu.ops.plan import plan_operation

h_in, w_in = 32, 48
plan = plan_operation("resize", ImageOptions(width=16, height=12, force=True),
                      h_in, w_in, 0, 3)
imgs = [np.random.default_rng(1000 * PID + j).integers(
            0, 256, (h_in, w_in, 3), dtype=np.uint8)
        for j in range(n_local)]
padded = np.stack([chain_mod.pad_to_bucket(a) for a in imgs])
gx = jax.make_array_from_process_local_data(sharding, padded,
                                            (n_global,) + padded.shape[1:])
gh = jax.make_array_from_process_local_data(
    sharding, np.full((n_local,), h_in, np.int32), (n_global,))
gw = jax.make_array_from_process_local_data(
    sharding, np.full((n_local,), w_in, np.int32), (n_global,))
gdyns = tuple(
    {{k: jax.make_array_from_process_local_data(
        sharding, np.asarray(v), (n_global,) + np.asarray(v).shape[1:])
      for k, v in d.items()}}
    for d in chain_mod._stack_dyns([plan] * n_local))
fn = jax.jit(chain_mod._run_chain, static_argnums=0)
y, _, _ = fn(plan.spec_key(), gx, gh, gw, gdyns)
for s in y.addressable_shards:
    local_idx = s.index[0].start - PID * n_local
    mine = np.asarray(s.data)[0, :plan.out_h, :plan.out_w]
    ref = chain_mod.run_single(imgs[local_idx], plan)  # single-device oracle
    assert np.array_equal(mine, ref), "sharded chain output diverged"
print("CHAIN_OK", (plan.out_h, plan.out_w))
"""


def test_two_process_fleet_psum_and_sharded_chain():
    """A REAL 2-process fleet (coordinator + worker subprocesses): global
    mesh, one cross-process psum, one dp-sharded chain step whose shards
    are bit-identical to the single-device oracle (SURVEY.md section 5.8;
    VERDICT r2 next #5)."""
    from tests.conftest import free_port

    port = free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER.format(pid=i, port=port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=_ROOT, env=env,
        )
        for i in range(2)
    ]
    # Poll both: if one worker dies early its peer blocks in
    # init_distributed until the timeout — report the dead worker's real
    # stderr instead of burning 5 minutes on a bare TimeoutExpired.
    import time

    outs = [None, None]
    deadline = time.monotonic() + 300
    try:
        while any(o is None for o in outs) and time.monotonic() < deadline:
            for i, p in enumerate(procs):
                if outs[i] is None and p.poll() is not None:
                    out, err = p.communicate()
                    outs[i] = (p.returncode, out, err)
            if any(o is not None and o[0] != 0 for o in outs):
                break  # a worker failed: don't wait out its blocked peer
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, p in enumerate(procs):
        if outs[i] is None:
            out, err = p.communicate()
            outs[i] = (p.returncode, out, err)

    fails = [(rc, out, err) for rc, out, err in outs if rc != 0]
    if any("distributed" in (err or "").lower() for _, _, err in fails):
        pytest.skip(f"jax.distributed unavailable here: {fails[0][2][-200:]}")
    assert not fails, "\n--- worker stderr ---\n".join(err[-2000:] for _, _, err in fails)
    for rc, out, err in outs:
        assert "PSUM_OK True" in out
        assert "CHAIN_OK" in out


_EXEC_WORKER = r"""
import threading
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from imaginary_tpu.parallel.mesh import init_distributed

PID = {pid}
init_distributed(coordinator_address="127.0.0.1:{port}",
                 num_processes=2, process_id=PID)
assert jax.process_count() == 2

# the SERVING executor inside a live fleet: micro-batch queue -> mesh
# dispatch on this process's local chips (get_mesh(local=True)), while the
# global 2-process backend stays up around it
from imaginary_tpu.engine import Executor, ExecutorConfig
from imaginary_tpu.ops import chain as chain_mod
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.ops.plan import plan_operation

ex = Executor(ExecutorConfig(window_ms=2.0, max_batch=8, use_mesh=True,
                             host_spill=False))
h_in, w_in = 32, 48
plan = plan_operation("resize", ImageOptions(width=16, height=12, force=True),
                      h_in, w_in, 0, 3)
rng = np.random.default_rng(77 + PID)
imgs = [rng.integers(0, 256, (h_in, w_in, 3), dtype=np.uint8) for _ in range(24)]
oracle = [chain_mod.run_single(a, plan) for a in imgs]

results = [None] * len(imgs)
def client(k):
    for j in range(k, len(imgs), 6):
        results[j] = ex.process(imgs[j], plan)

threads = [threading.Thread(target=client, args=(k,)) for k in range(6)]
for t in threads: t.start()
for t in threads: t.join()
ex.shutdown()
for got, want in zip(results, oracle):
    assert got is not None and np.array_equal(got, want), "fleet executor output diverged"
assert ex.stats.items == len(imgs)
print("EXEC_FLEET_OK", ex.stats.items, ex.stats.batches)
"""


def test_two_process_fleet_serving_executors():
    """Both fleet processes run the SERVING executor concurrently —
    micro-batch queue, batch formation, mesh dispatch — against the
    single-device oracle (VERDICT r4 next #7: test_distributed proved
    init/psum/sharded-chain but never the Executor across processes)."""
    import time

    from tests.conftest import free_port

    port = free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _EXEC_WORKER.format(pid=i, port=port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=_ROOT, env=env,
        )
        for i in range(2)
    ]
    outs = [None, None]
    deadline = time.monotonic() + 300
    try:
        while any(o is None for o in outs) and time.monotonic() < deadline:
            for i, p in enumerate(procs):
                if outs[i] is None and p.poll() is not None:
                    out, err = p.communicate()
                    outs[i] = (p.returncode, out, err)
            if any(o is not None and o[0] != 0 for o in outs):
                break
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, p in enumerate(procs):
        if outs[i] is None:
            out, err = p.communicate()
            outs[i] = (p.returncode, out, err)
    fails = [(rc, out, err) for rc, out, err in outs if rc != 0]
    if any("distributed" in (err or "").lower() for _, _, err in fails):
        pytest.skip(f"jax.distributed unavailable here: {fails[0][2][-200:]}")
    assert not fails, "\n--- worker stderr ---\n".join(err[-2000:] for _, _, err in fails)
    for rc, out, err in outs:
        assert "EXEC_FLEET_OK 24" in out


_MESH_CHAIN_WORKER = r"""
import threading
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from imaginary_tpu.parallel.mesh import init_distributed

PID = {pid}
init_distributed(coordinator_address="127.0.0.1:{port}",
                 num_processes=2, process_id=PID)
assert jax.process_count() == 2
# XLA_FLAGS forced 2 host devices per process: the serving executor's
# local mesh is (batch=2, spatial=1), so formed micro-batches genuinely
# SHARD across devices instead of degenerating to a 1-chip mesh
assert len(jax.local_devices()) == 2, jax.local_devices()

from imaginary_tpu.engine import Executor, ExecutorConfig
from imaginary_tpu.ops import chain as chain_mod
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.ops.plan import plan_operation

ex = Executor(ExecutorConfig(window_ms=4.0, max_batch=8, use_mesh=True,
                             host_spill=False))
assert ex._mesh_batch == 2, ex._mesh_batch  # batch axis spans both chips
h_in, w_in = 32, 48
plan = plan_operation("resize", ImageOptions(width=16, height=12, force=True),
                      h_in, w_in, 0, 3)
rng = np.random.default_rng(900 + PID)
imgs = [rng.integers(0, 256, (h_in, w_in, 3), dtype=np.uint8) for _ in range(24)]
oracle = [chain_mod.run_single(a, plan) for a in imgs]

results = [None] * len(imgs)
def client(k):
    for j in range(k, len(imgs), 6):
        results[j] = ex.process(imgs[j], plan)

threads = [threading.Thread(target=client, args=(k,)) for k in range(6)]
for t in threads: t.start()
for t in threads: t.join()
ex.shutdown()
for got, want in zip(results, oracle):
    assert got is not None and np.array_equal(got, want), "sharded serving chain diverged"
assert ex.stats.items == len(imgs)
assert ex.stats.batches < len(imgs)  # batching actually formed groups
print("MESH_CHAIN_OK", ex._mesh_batch, ex.stats.batches)
"""


def _run_fleet_pair(worker_src, port, extra_env=None, budget_s=300):
    """Launch two pinned fleet subprocesses and poll both (a dead worker
    would otherwise wedge its peer inside init_distributed)."""
    import time

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker_src.format(pid=i, port=port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=_ROOT, env=env,
        )
        for i in range(2)
    ]
    outs = [None, None]
    deadline = time.monotonic() + budget_s
    try:
        while any(o is None for o in outs) and time.monotonic() < deadline:
            for i, p in enumerate(procs):
                if outs[i] is None and p.poll() is not None:
                    out, err = p.communicate()
                    outs[i] = (p.returncode, out, err)
            if any(o is not None and o[0] != 0 for o in outs):
                break
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, p in enumerate(procs):
        if outs[i] is None:
            out, err = p.communicate()
            outs[i] = (p.returncode, out, err)
    fails = [(rc, out, err) for rc, out, err in outs if rc != 0]
    if any("distributed" in (err or "").lower() for _, _, err in fails):
        pytest.skip(f"jax.distributed unavailable here: {fails[0][2][-200:]}")
    assert not fails, "\n--- worker stderr ---\n".join(
        err[-2000:] for _, _, err in fails)
    return outs


def test_two_process_fleet_sharded_serving_chain():
    """ISSUE 20: the 2-process gloo fleet runs one SHARDED chain through
    the serving Executor mesh path — 2 forced host devices per process,
    use_mesh batch-shards every formed micro-batch across them, outputs
    bit-identical to the single-device oracle."""
    from tests.conftest import free_port

    outs = _run_fleet_pair(
        _MESH_CHAIN_WORKER, free_port(),
        extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"})
    for rc, out, err in outs:
        assert "MESH_CHAIN_OK 2" in out


def test_cli_flags_thread_through():
    from imaginary_tpu.cli import build_parser, options_from_args

    args = build_parser().parse_args([
        "--distributed", "--coordinator-address", "10.0.0.1:1234",
        "--num-processes", "4", "--process-id", "2",
    ])
    o = options_from_args(args)
    assert o.distributed
    assert o.coordinator_address == "10.0.0.1:1234"
    assert o.num_processes == 4
    assert o.process_id == 2


def test_mesh_hosts_flags_thread_through():
    from imaginary_tpu.cli import build_parser, options_from_args

    args = build_parser().parse_args([
        "--mesh-hosts", "2", "--coordinator-address", "10.0.0.1:1234",
        "--process-id", "1", "--workers", "1",
    ])
    o = options_from_args(args)
    assert o.mesh_hosts == 2
    assert o.process_id == 1

    # a serving mesh needs a coordinator, a pinned process id, and one
    # serving process per host (that process owns the host's chips)
    with pytest.raises(SystemExit):
        options_from_args(build_parser().parse_args(
            ["--mesh-hosts", "2", "--process-id", "0", "--workers", "1"]))
    with pytest.raises(SystemExit):
        options_from_args(build_parser().parse_args(
            ["--mesh-hosts", "2", "--coordinator-address", "10.0.0.1:1",
             "--workers", "1"]))
    with pytest.raises(SystemExit):
        options_from_args(build_parser().parse_args(
            ["--mesh-hosts", "2", "--coordinator-address", "10.0.0.1:1",
             "--process-id", "0", "--workers", "2"]))


def test_mesh_hosts_serving_boot_two_hosts():
    """Tentpole (e): `--mesh-hosts` wires init_distributed into serving
    boot. Two real `python -m imaginary_tpu.cli` processes rendezvous as
    a 2-host jax.distributed fleet, then each serves a real resize over
    HTTP — proving the global backend and the HTTP plane coexist."""
    import json
    import time
    import urllib.request

    from tests.conftest import fixture_bytes, free_port

    coord = free_port()
    p0, p1 = free_port(), free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "imaginary_tpu.cli", "--workers", "1",
             "--mesh-hosts", "2",
             "--coordinator-address", f"127.0.0.1:{coord}",
             "--process-id", str(i), "--port", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=_ROOT, env=env,
        )
        for i, port in enumerate((p0, p1))
    ]
    try:
        body = fixture_bytes("imaginary.jpg")
        deadline = time.monotonic() + 240
        answers = {}
        while time.monotonic() < deadline and len(answers) < 2:
            for port in (p0, p1):
                if port in answers:
                    continue
                if any(p.poll() is not None for p in procs):
                    break  # a host died: fail fast with its stderr
                try:
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{port}/resize?width=64",
                        data=body, method="POST",
                        headers={"Content-Type": "image/jpeg"})
                    with urllib.request.urlopen(req, timeout=30.0) as r:
                        assert r.status == 200
                        answers[port] = r.read()
                except (urllib.error.URLError, ConnectionError, OSError):
                    time.sleep(0.5)
            if any(p.poll() is not None for p in procs):
                break
        dead = [p for p in procs if p.poll() is not None]
        if dead:
            err = dead[0].communicate()[1]
            if "distributed" in (err or "").lower():
                pytest.skip(f"jax.distributed unavailable: {err[-200:]}")
            raise AssertionError("mesh host died:\n" + err[-2000:])
        assert len(answers) == 2
        # identical pipeline on both hosts: byte-identical answers
        assert answers[p0] == answers[p1]
    finally:
        import signal as _signal

        for p in procs:
            if p.poll() is None:
                p.send_signal(_signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
