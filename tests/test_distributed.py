"""Multi-host initialization hook (SURVEY.md section 5.8; VERDICT r1 next #9).

jax.distributed.initialize is process-global and incompatible with the
already-initialized test backend, so the test drives the real code path in a
pinned subprocess: a 1-process "fleet" whose coordinator is itself — the
same call shape a TPU pod worker uses, minus auto-discovery.
"""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import jax
jax.config.update("jax_platforms", "cpu")
from imaginary_tpu.parallel.mesh import get_mesh, init_distributed

init_distributed(coordinator_address="127.0.0.1:{port}",
                 num_processes=1, process_id=0)
init_distributed()  # idempotent: second call must be a no-op
assert jax.process_count() == 1
mesh = get_mesh()
print("DIST_OK", jax.process_count(), dict(zip(mesh.axis_names, mesh.devices.shape)))
"""


def test_init_distributed_single_process_fleet():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-c", _CHILD.format(port=port)],
        capture_output=True, text=True, timeout=240, cwd=_ROOT, env=env,
    )
    if r.returncode != 0 and "distributed" in (r.stderr or "").lower():
        pytest.skip(f"jax.distributed unavailable here: {r.stderr[-200:]}")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "DIST_OK 1" in r.stdout


def test_cli_flags_thread_through():
    from imaginary_tpu.cli import build_parser, options_from_args

    args = build_parser().parse_args([
        "--distributed", "--coordinator-address", "10.0.0.1:1234",
        "--num-processes", "4", "--process-id", "2",
    ])
    o = options_from_args(args)
    assert o.distributed
    assert o.coordinator_address == "10.0.0.1:1234"
    assert o.num_processes == 4
    assert o.process_id == 2
