"""Options-model tests (modeled on options_test.go)."""

from imaginary_tpu.options import (
    ImageOptions,
    apply_aspect_ratio,
    parse_aspect_ratio,
    should_transform_by_aspect_ratio,
    transform_by_aspect_ratio,
)


def test_parse_aspect_ratio():
    assert parse_aspect_ratio("16:9") == {"width": 16, "height": 9}
    assert parse_aspect_ratio(" 4:3 ") == {"width": 4, "height": 3}
    assert parse_aspect_ratio("16") is None
    assert parse_aspect_ratio("") is None
    assert parse_aspect_ratio("a:b") == {"width": 0, "height": 0}


def test_should_transform():
    assert should_transform_by_aspect_ratio(100, 0)
    assert should_transform_by_aspect_ratio(0, 100)
    assert not should_transform_by_aspect_ratio(100, 100)
    assert not should_transform_by_aspect_ratio(0, 0)


def test_transform_by_aspect_ratio_reference_math():
    # The reference uses truncating division: w // arW * arH (options.go:92-94)
    w, h = transform_by_aspect_ratio(1600, 0, {"width": 16, "height": 9})
    assert (w, h) == (1600, 900)
    w, h = transform_by_aspect_ratio(0, 900, {"width": 16, "height": 9})
    assert (w, h) == (1600, 900)
    # truncation behavior: 333 // 16 * 9 = 180 (not round(333*9/16)=187)
    w, h = transform_by_aspect_ratio(333, 0, {"width": 16, "height": 9})
    assert (w, h) == (333, 180)


def test_apply_aspect_ratio():
    o = ImageOptions(width=1600, aspect_ratio="16:9")
    assert apply_aspect_ratio(o) == (1600, 900)
    # both dims given: ratio ignored
    o = ImageOptions(width=100, height=100, aspect_ratio="16:9")
    assert apply_aspect_ratio(o) == (100, 100)
    # no ratio: unchanged
    o = ImageOptions(width=100)
    assert apply_aspect_ratio(o) == (100, 0)


def test_parse_aspect_ratio_go_atoi_strictness():
    # Go strconv.Atoi rejects inner padding and underscores -> 0
    assert parse_aspect_ratio("16 : 9") == {"width": 0, "height": 0}
    assert parse_aspect_ratio("1_6:9") == {"width": 0, "height": 9}
    assert parse_aspect_ratio("+16:9") == {"width": 16, "height": 9}
