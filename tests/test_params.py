"""Parameter-coercion tests, modeled on the reference's table-driven suite
(params_test.go:12-157 and :283-407)."""

import pytest

from imaginary_tpu.options import Colorspace, Extend, Gravity
from imaginary_tpu.params import (
    ParamError,
    build_params_from_operation,
    build_params_from_query,
    parse_bool,
    parse_color,
    parse_colorspace,
    parse_extend_mode,
    parse_float,
    parse_gravity,
    parse_int,
    parse_json_operations,
)
from imaginary_tpu.options import PipelineOperation


def test_read_params():
    q = {
        "width": "100",
        "height": "80",
        "noreplicate": "1",
        "opacity": "0.2",
        "text": "hello",
        "background": "255,10,20",
        "interlace": "true",
    }
    p = build_params_from_query(q)
    assert p.width == 100
    assert p.height == 80
    assert p.no_replicate is True
    assert p.opacity == pytest.approx(0.2)
    assert p.text == "hello"
    assert p.background == (255, 10, 20)
    assert p.interlace is True
    # builder default (params.go:356)
    assert p.extend is Extend.COPY


@pytest.mark.parametrize(
    "value,expected",
    [("1", 1), ("0100", 100), ("-100", 100), ("99.02", 99), ("99.9", 100), ("", 0)],
)
def test_parse_int(value, expected):
    assert parse_int(value) == expected


@pytest.mark.parametrize(
    "value,expected",
    [("1.1", 1.1), ("01.1", 1.1), ("-1.10", 1.10), ("99.999999", 99.999999), ("", 0.0)],
)
def test_parse_float(value, expected):
    assert parse_float(value) == pytest.approx(expected)


@pytest.mark.parametrize(
    "value,expected",
    [("true", True), ("false", False), ("1", True), ("-1", None), ("0", False),
     ("1.1", None), ("0.0", None), ("no", None), ("yes", None), ("", False)],
)
def test_parse_bool(value, expected):
    if expected is None:
        with pytest.raises(ParamError):
            parse_bool(value)
    else:
        assert parse_bool(value) is expected


@pytest.mark.parametrize(
    "value,expected",
    [
        ("200,100,20", (200, 100, 20)),
        ("0,280,200", (0, 255, 200)),
        (" -1, 256 , 50", (0, 255, 50)),
        (" a, 20 , &hel0", (0, 20, 0)),
        ("", ()),
    ],
)
def test_parse_color(value, expected):
    assert parse_color(value) == expected


@pytest.mark.parametrize(
    "value,expected",
    [
        ("white", Extend.WHITE),
        ("black", Extend.BLACK),
        ("copy", Extend.COPY),
        ("mirror", Extend.MIRROR),
        ("background", Extend.BACKGROUND),
        ("lastpixel", Extend.LAST),
        (" Black ", Extend.BLACK),
        ("unknown", Extend.MIRROR),
        ("", Extend.MIRROR),
    ],
)
def test_parse_extend(value, expected):
    assert parse_extend_mode(value) is expected


@pytest.mark.parametrize(
    "value,expected",
    [
        ("north", Gravity.NORTH),
        ("south", Gravity.SOUTH),
        ("east", Gravity.EAST),
        ("west", Gravity.WEST),
        ("smart", Gravity.SMART),
        (" SMART ", Gravity.SMART),
        ("centre", Gravity.CENTRE),
        ("bogus", Gravity.CENTRE),
        ("", Gravity.CENTRE),
    ],
)
def test_parse_gravity(value, expected):
    assert parse_gravity(value) is expected


def test_parse_colorspace():
    assert parse_colorspace("bw") is Colorspace.BW
    assert parse_colorspace("srgb") is Colorspace.SRGB
    assert parse_colorspace("") is Colorspace.SRGB


class TestCoercion:
    """Mirrors TestCoerceTypeFns (params_test.go:283-407): each typed coercer
    accepts JSON-native values as well as strings."""

    def test_int_accepts_json_number(self):
        p = build_params_from_operation(PipelineOperation(params={"width": 300}))
        assert p.width == 300
        p = build_params_from_operation(PipelineOperation(params={"width": 300.7}))
        assert p.width == 300  # Go float64->int truncates

    def test_bool_accepts_json_bool(self):
        p = build_params_from_operation(PipelineOperation(params={"force": True}))
        assert p.force is True
        assert p.is_defined("force")

    def test_float_accepts_json_number(self):
        p = build_params_from_operation(PipelineOperation(params={"opacity": 0.5}))
        assert p.opacity == pytest.approx(0.5)

    def test_string_rejects_number(self):
        with pytest.raises(ParamError):
            build_params_from_operation(PipelineOperation(params={"text": 5}))

    def test_bool_rejects_number(self):
        with pytest.raises(ParamError):
            build_params_from_operation(PipelineOperation(params={"force": 5}))

    def test_unknown_keys_ignored(self):
        p = build_params_from_query({"bogus": "1", "width": "10"})
        assert p.width == 10

    def test_bad_value_raises(self):
        with pytest.raises(ParamError):
            build_params_from_query({"width": "nan-ish"})


def test_parse_json_operations():
    ops = parse_json_operations(
        '[{"operation": "crop", "params": {"width": 300}},'
        ' {"operation": "convert", "ignore_failure": true, "params": {"type": "webp"}}]'
    )
    assert len(ops) == 2
    assert ops[0].name == "crop"
    assert ops[0].params == {"width": 300}
    assert ops[1].ignore_failure is True


def test_parse_json_operations_empty():
    assert parse_json_operations("") == []
    assert parse_json_operations("[") == []  # len < 2 short-circuits (params.go:413)


def test_parse_json_operations_unknown_field():
    with pytest.raises(ParamError):
        parse_json_operations('[{"operation": "crop", "bogus": 1}]')


def test_tri_state_defined_tracking():
    p = build_params_from_query({"nocrop": "false"})
    assert p.no_crop is False
    assert p.is_defined("no_crop")
    p2 = build_params_from_query({})
    assert not p2.is_defined("no_crop")


class TestHardenedEdgeCases:
    """Regressions for review findings: NaN/Inf, unicode digits, typed
    pipeline JSON fields must all render as 400s, never crash."""

    @pytest.mark.parametrize("bad", ["nan", "inf", "-inf", "NaN", "Infinity"])
    def test_nan_inf_rejected(self, bad):
        with pytest.raises(ParamError):
            build_params_from_query({"width": bad})

    def test_unicode_digit_color_is_zero(self):
        assert parse_color("²") == (0,)  # superscript two
        assert parse_color("٣") == (0,)  # arabic-indic three

    def test_json_nan_constant_rejected(self):
        with pytest.raises(ParamError):
            parse_json_operations('[{"operation": "resize", "params": {"width": NaN}}]')

    def test_ignore_failure_must_be_bool(self):
        with pytest.raises(ParamError):
            parse_json_operations('[{"operation": "resize", "ignore_failure": "false"}]')

    def test_operation_name_must_be_string(self):
        with pytest.raises(ParamError):
            parse_json_operations('[{"operation": 5}]')

    def test_float_nan_in_pipeline_params(self):
        from imaginary_tpu.params import _coerce_int
        with pytest.raises(ParamError):
            _coerce_int(float("nan"))
