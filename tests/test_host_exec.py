"""Host SIMD spill backend: correctness vs the device path, and the
executor's cost-model placement policy (engine/host_exec.py, executor.py)."""

import numpy as np
import pytest

from imaginary_tpu.engine import Executor, ExecutorConfig, host_exec
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.ops import chain
from imaginary_tpu.ops.plan import plan_operation


from tests.conftest import psnr as _psnr


@pytest.fixture(scope="module")
def img():
    rng = np.random.default_rng(42)
    # smooth-ish content: kernel differences on pure noise are worst-case
    base = rng.integers(0, 256, (34, 60, 3), np.uint8)
    big = np.kron(base, np.ones((8, 8, 1), np.uint8))[:270, :480]
    return np.ascontiguousarray(big)


CASES = [
    ("resize", ImageOptions(width=300, height=200)),
    ("crop", ImageOptions(width=100, height=120)),
    ("fit", ImageOptions(width=200, height=200)),
    ("extract", ImageOptions(top=10, left=20, area_width=200, area_height=100)),
    ("flip", ImageOptions()),
    ("flop", ImageOptions()),
    ("rotate", ImageOptions(rotate=90)),
    ("blur", ImageOptions(sigma=2.0)),
    ("zoom", ImageOptions(factor=2)),
]


@pytest.mark.parametrize("name,o", CASES, ids=[c[0] for c in CASES])
def test_host_matches_device(img, name, o):
    plan = plan_operation(name, o, img.shape[0], img.shape[1], 1, 3)
    assert host_exec.can_execute(plan)
    hy = host_exec.run(img, plan)
    dy = chain.run_single(img, plan)
    assert hy.shape == dy.shape
    assert _psnr(hy, dy) > 28.0, f"{name}: host/device divergence too large"


def test_smartcrop_never_spills(img):
    o = ImageOptions(width=64, height=64)
    plan = plan_operation("smartcrop", o, img.shape[0], img.shape[1], 1, 3)
    # interpretable on host (full-host deployments)...
    assert host_exec.can_execute(plan, for_spill=False)
    # ...but excluded from load-dependent placement: the crop window must
    # not depend on link pressure
    assert not host_exec.can_execute(plan, for_spill=True)


def test_spill_triggers_when_device_saturated(img):
    from imaginary_tpu.engine.executor import last_placement, reset_placement

    ex = Executor(ExecutorConfig(host_spill=True, spill_factor=1.0))
    try:
        # simulate a measured slow link: 1s per item drain
        ex._device_ms_per_mb = 10000.0
        o = ImageOptions(width=64, height=48)
        plan = plan_operation("resize", o, img.shape[0], img.shape[1], 1, 3)
        reset_placement()
        out = ex.process(img, plan)
        assert out.shape == (48, 64, 3)
        assert ex.stats.spilled == 1
        assert ex.stats.items == 0  # never reached the device queue
        assert last_placement() == "host"  # X-Imaginary-Backend source
    finally:
        ex.shutdown()


def test_cost_model_is_size_aware(img):
    """Placement estimates are per-unit (wire MB / source Mpix): a 4K-class
    item carries a ~600x larger wait/cost footprint than a thumbnail-class
    one, and a 4K item sitting in the device queue delays a small follower
    by ITS byte count — one global per-item EWMA could express neither
    (r4: the 4K pipeline route was mis-costed by exactly this)."""
    ex = Executor(ExecutorConfig(host_spill=True, probe_interval=10**9))
    try:
        from imaginary_tpu.engine.executor import _Item

        o = ImageOptions(width=64, height=48)
        small = _Item(img, plan_operation("resize", o, img.shape[0], img.shape[1], 1, 3))
        big_arr = np.zeros((2160, 3840, 3), np.uint8)
        big = _Item(big_arr, plan_operation("resize", ImageOptions(width=1280),
                                            2160, 3840, 0, 3))
        assert big.wire_mb > 50 * small.wire_mb  # 270x480 vs 4K source
        assert big.mpix > 50 * small.mpix
        # measured-tunnel-class rates: both sizes prefer the host...
        ex._device_ms_per_mb = 33.0
        ex._host_ms_per_mpix = 8.0
        assert ex._should_spill(big)
        assert ex._should_spill(small)
        # ...PCIe-class rates: neither spills...
        ex._device_ms_per_mb = 0.05
        assert not ex._should_spill(big)
        assert not ex._should_spill(small)
        # ...and one queued 4K item's estimated MILLISECONDS (not its item
        # count) are what push a small follower over the spill threshold
        assert not ex._should_spill(small)
        ex._device_ms_per_mb = 1.0
        ex._owed_ms = big.wire_mb * 1.0  # a queued 4K item's worth
        assert ex._should_spill(small)
        ex._owed_ms = small.wire_mb * 1.0  # same queue LENGTH, tiny bytes
        assert not ex._should_spill(small)
    finally:
        ex._owed_ms = 0.0
        ex.shutdown()


def test_shadow_probes_rate_limited_by_wall_clock(img):
    """The probe count gate is backed by probe_min_interval_s: on a 1-CPU
    host each shadow's H2D staging steals ~20 ms from whatever request it
    coincides with (measured as the latency bench's remaining p99
    stragglers), so within one interval at most ONE shadow ships no
    matter how many count slots pass — and stale-but-CHEAP slots must not
    feed the 16-slot ungated escape (that would re-open the very cadence
    the gate closes, minus its budget/warmth safety checks)."""
    from imaginary_tpu.ops import chain as chain_mod

    o = ImageOptions(width=64, height=48)
    plan = plan_operation("resize", o, img.shape[0], img.shape[1], 1, 3)
    chain_mod.run_single(img, plan)  # warm: the cheap gate checks the cache
    # spill_factor ~0 forces every request to spill while the small rate
    # keeps the probe well under probe_budget_ms — the cheap path is
    # genuinely open and ONLY the wall clock blocks it
    ex = Executor(ExecutorConfig(host_spill=True, spill_factor=0.001,
                                 probe_interval=2, probe_min_interval_s=3600.0))
    try:
        ex._device_ms_per_mb = 10.0
        ex._drain_floor_ms = 5.0
        for _ in range(40):
            ex.process(img, plan)
        assert ex.stats.spilled == 40
        # 20 count slots: the first ships (never probed before), the other
        # 19 are cheap+stale -> blocked, and they must NOT accumulate into
        # the escape (19 > 16 would ship a second, ungated, shadow)
        assert ex.stats.shadow_probes == 1
        # skipped==0 proves the ship rode the CHEAP path (budget+warmth
        # open) — an escape-path ship would leave a nonzero residue
        assert ex._probe_slots_skipped == 0
    finally:
        ex.shutdown()


def test_no_spill_when_device_fast(img):
    from imaginary_tpu.engine.executor import last_placement, reset_placement

    ex = Executor(ExecutorConfig(host_spill=True))
    try:
        ex._device_ms_per_mb = 0.01  # fast PCIe-class link
        o = ImageOptions(width=64, height=48)
        plan = plan_operation("resize", o, img.shape[0], img.shape[1], 1, 3)
        reset_placement()
        out = ex.process(img, plan)
        assert out.shape == (48, 64, 3)
        assert ex.stats.spilled == 0
        assert ex.stats.items == 1
        assert last_placement() == "device"
    finally:
        ex.shutdown()


def test_embed_modes_match_device(img):
    from imaginary_tpu.options import Extend

    small = img[:100, :150]
    for extend in (Extend.MIRROR, Extend.COPY, Extend.WHITE, Extend.BLACK,
                   Extend.BACKGROUND):
        o = ImageOptions(width=300, height=200, embed=True, extend=extend,
                         background=(10, 200, 30))
        o.mark_defined("embed")
        plan = plan_operation("resize", o, 100, 150, 1, 3)
        hy = host_exec.run(small, plan)
        dy = chain.run_single(small, plan)
        assert hy.shape == dy.shape
        assert _psnr(hy, dy) > 28.0, extend


def test_watermark_composite_matches_device(img):
    o = ImageOptions(width=200, text="hello tpu", opacity=0.7)
    plan = plan_operation("watermark", o, img.shape[0], img.shape[1], 1, 3)
    if not host_exec.can_execute(plan):
        pytest.skip("composite not host-executable")
    hy = host_exec.run(img, plan)
    dy = chain.run_single(img, plan)
    assert hy.shape == dy.shape
    assert _psnr(hy, dy) > 25.0
