"""Host SIMD spill backend: correctness vs the device path, and the
executor's cost-model placement policy (engine/host_exec.py, executor.py)."""

import numpy as np
import pytest

from imaginary_tpu.engine import Executor, ExecutorConfig, host_exec
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.ops import chain
from imaginary_tpu.ops.plan import plan_operation


from tests.conftest import psnr as _psnr


@pytest.fixture(scope="module")
def img():
    rng = np.random.default_rng(42)
    # smooth-ish content: kernel differences on pure noise are worst-case
    base = rng.integers(0, 256, (34, 60, 3), np.uint8)
    big = np.kron(base, np.ones((8, 8, 1), np.uint8))[:270, :480]
    return np.ascontiguousarray(big)


CASES = [
    ("resize", ImageOptions(width=300, height=200)),
    ("crop", ImageOptions(width=100, height=120)),
    ("fit", ImageOptions(width=200, height=200)),
    ("extract", ImageOptions(top=10, left=20, area_width=200, area_height=100)),
    ("flip", ImageOptions()),
    ("flop", ImageOptions()),
    ("rotate", ImageOptions(rotate=90)),
    ("blur", ImageOptions(sigma=2.0)),
    ("zoom", ImageOptions(factor=2)),
    # pure enlarge and mixed shrink/enlarge: the separable precomputed-tap
    # resample paths (native or numpy taps), graded against the device
    ("enlarge", ImageOptions(width=600, height=400)),
    ("resize-mixed", ImageOptions(width=600, height=100, force=True)),
]


@pytest.mark.parametrize("name,o", CASES, ids=[c[0] for c in CASES])
def test_host_matches_device(img, name, o):
    name = name.split("-")[0]  # "resize-mixed" is a resize with mixed axes
    plan = plan_operation(name, o, img.shape[0], img.shape[1], 1, 3)
    assert host_exec.can_execute(plan)
    hy = host_exec.run(img, plan)
    dy = chain.run_single(img, plan)
    assert hy.shape == dy.shape
    assert _psnr(hy, dy) > 28.0, f"{name}: host/device divergence too large"


class TestSeparableResample:
    """The spill path's resampler: precomputed-tap numpy fallback and the
    native SIMD entry point (when buildable), both graded against the
    dense device-port math they replaced."""

    def _dense_reference(self, x, dh, dw, kernel):
        # the pre-rewrite dense sampling-matrix port, kept here as the
        # oracle: same weights as ops/stages.sample_matrix
        f = x.astype(np.float32)

        def mat(out_n, in_n, kind):
            y = np.arange(out_n, dtype=np.float32)[:, None]
            k = np.arange(in_n, dtype=np.float32)[None, :]
            scale = out_n / in_n
            centre = (y + 0.5) / scale - 0.5
            stretch = max(1.0, 1.0 / scale)
            wts = host_exec._np_kernel(kind, (k - centre) / stretch)
            norm = wts.sum(axis=-1, keepdims=True)
            return np.where(norm > 1e-6, wts / np.maximum(norm, 1e-6), 0.0)

        t = np.einsum("yk,kwc->ywc", mat(dh, f.shape[0], kernel), f)
        return np.einsum("xw,ywc->yxc", mat(dw, f.shape[1], kernel), t)

    GEOMS = [(120, 300, "lanczos3"), (400, 90, "cubic"), (301, 481, "linear"),
             (500, 600, "lanczos3"), (33, 77, "nearest"), (90, 120, "lanczos2")]

    def test_numpy_taps_match_dense_port(self, img):
        for dh, dw, kernel in self.GEOMS:
            ref = np.clip(self._dense_reference(img, dh, dw, kernel) + 0.5,
                          0, 255).astype(np.uint8)
            got = np.clip(host_exec._np_resize(img, dh, dw, kernel) + 0.5,
                          0, 255).astype(np.uint8)
            assert got.shape == ref.shape
            diff = np.abs(ref.astype(int) - got.astype(int)).max()
            assert diff <= 1, f"{dh}x{dw} {kernel}: maxdiff {diff}"

    @pytest.fixture(scope="class")
    def native_resize(self):
        from imaginary_tpu.codecs import native_backend

        if not native_backend.resample_available():
            try:
                from imaginary_tpu.native.build import build_resample

                build_resample(verbose=False)
            except Exception as e:
                pytest.skip(f"native resample build failed: {e}")
            import importlib

            importlib.reload(native_backend)
            if not native_backend.resample_available():
                pytest.skip("native resampler unavailable after build")
        return native_backend.resize_separable

    def test_native_matches_numpy_taps(self, img, native_resize):
        for dh, dw, kernel in self.GEOMS:
            ref = np.clip(host_exec._np_resize(img, dh, dw, kernel) + 0.5,
                          0, 255).astype(np.uint8)
            got = native_resize(img, dh, dw, kernel)
            assert got.shape == ref.shape
            diff = np.abs(ref.astype(int) - got.astype(int)).max()
            assert diff <= 1, f"{dh}x{dw} {kernel}: maxdiff {diff}"

    def test_native_concurrent_calls_consistent(self, img, native_resize):
        # the entry point releases the GIL; hammer it from threads and
        # check every result is identical to the serial answer
        import threading

        ref = native_resize(img, 190, 333, "lanczos3")
        errs = []

        def worker():
            for _ in range(5):
                out = native_resize(img, 190, 333, "lanczos3")
                if not np.array_equal(out, ref):
                    errs.append("divergent result under concurrency")

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs

    def test_fallback_when_native_absent(self, img, monkeypatch):
        # simulate a host where no native module built: the interpreter
        # must serve identically-shaped output via the numpy taps
        monkeypatch.setattr(host_exec, "_NATIVE_RESAMPLE", False)
        o = ImageOptions(width=600, height=400)
        plan = plan_operation("enlarge", o, img.shape[0], img.shape[1], 1, 3)
        hy = host_exec.run(img, plan)
        dy = chain.run_single(img, plan)
        assert hy.shape == dy.shape
        assert _psnr(hy, dy) > 28.0

    def test_tap_tables_are_cached(self):
        host_exec._tap_table.cache_clear()
        host_exec._np_resize(np.zeros((50, 60, 3), np.uint8), 20, 30, "cubic")
        host_exec._np_resize(np.zeros((50, 60, 3), np.uint8), 20, 30, "cubic")
        info = host_exec._tap_table.cache_info()
        assert info.misses == 2  # one per axis
        assert info.hits == 2  # second call reused both


def test_smartcrop_never_spills(img):
    o = ImageOptions(width=64, height=64)
    plan = plan_operation("smartcrop", o, img.shape[0], img.shape[1], 1, 3)
    # interpretable on host (full-host deployments)...
    assert host_exec.can_execute(plan, for_spill=False)
    # ...but excluded from load-dependent placement: the crop window must
    # not depend on link pressure
    assert not host_exec.can_execute(plan, for_spill=True)


def test_spill_triggers_when_device_saturated(img):
    from imaginary_tpu.engine.executor import last_placement, reset_placement

    ex = Executor(ExecutorConfig(host_spill=True, spill_factor=1.0))
    try:
        # simulate a measured slow link: 1s per item drain
        ex._device_ms_per_mb = 10000.0
        o = ImageOptions(width=64, height=48)
        plan = plan_operation("resize", o, img.shape[0], img.shape[1], 1, 3)
        reset_placement()
        out = ex.process(img, plan)
        assert out.shape == (48, 64, 3)
        assert ex.stats.spilled == 1
        assert ex.stats.items == 0  # never reached the device queue
        assert last_placement() == "host"  # X-Imaginary-Backend source
    finally:
        ex.shutdown()


def test_cost_model_is_size_aware(img):
    """Placement estimates are per-unit (wire MB / source Mpix): a 4K-class
    item carries a ~600x larger wait/cost footprint than a thumbnail-class
    one, and a 4K item sitting in the device queue delays a small follower
    by ITS byte count — one global per-item EWMA could express neither
    (r4: the 4K pipeline route was mis-costed by exactly this)."""
    ex = Executor(ExecutorConfig(host_spill=True, probe_interval=10**9))
    try:
        from imaginary_tpu.engine.executor import _Item

        o = ImageOptions(width=64, height=48)
        small = _Item(img, plan_operation("resize", o, img.shape[0], img.shape[1], 1, 3))
        big_arr = np.zeros((2160, 3840, 3), np.uint8)
        big = _Item(big_arr, plan_operation("resize", ImageOptions(width=1280),
                                            2160, 3840, 0, 3))
        assert big.wire_mb > 50 * small.wire_mb  # 270x480 vs 4K source
        assert big.mpix > 50 * small.mpix
        # measured-tunnel-class rates: both sizes prefer the host...
        ex._device_ms_per_mb = 33.0
        ex._host_ms_per_mpix = 8.0
        assert ex._should_spill(big)
        assert ex._should_spill(small)
        # ...PCIe-class rates: neither spills...
        ex._device_ms_per_mb = 0.05
        assert not ex._should_spill(big)
        assert not ex._should_spill(small)
        # ...and one queued 4K item's estimated MILLISECONDS (not its item
        # count) are what push a small follower over the spill threshold
        assert not ex._should_spill(small)
        ex._device_ms_per_mb = 1.0
        ex._owed_ms = big.wire_mb * 1.0  # a queued 4K item's worth
        assert ex._should_spill(small)
        ex._owed_ms = small.wire_mb * 1.0  # same queue LENGTH, tiny bytes
        assert not ex._should_spill(small)
    finally:
        ex._owed_ms = 0.0
        ex.shutdown()


def test_shadow_probes_rate_limited_by_wall_clock(img):
    """The probe count gate is backed by probe_min_interval_s: on a 1-CPU
    host each shadow's H2D staging steals ~20 ms from whatever request it
    coincides with (measured as the latency bench's remaining p99
    stragglers), so within one interval at most ONE shadow ships no
    matter how many count slots pass — and stale-but-CHEAP slots must not
    feed the 16-slot ungated escape (that would re-open the very cadence
    the gate closes, minus its budget/warmth safety checks)."""
    from imaginary_tpu.ops import chain as chain_mod

    o = ImageOptions(width=64, height=48)
    plan = plan_operation("resize", o, img.shape[0], img.shape[1], 1, 3)
    chain_mod.run_single(img, plan)  # warm: the cheap gate checks the cache
    # spill_factor ~0 forces every request to spill while the small rate
    # keeps the probe well under probe_budget_ms — the cheap path is
    # genuinely open and ONLY the wall clock blocks it
    ex = Executor(ExecutorConfig(host_spill=True, spill_factor=0.001,
                                 probe_interval=2, probe_min_interval_s=3600.0))
    try:
        ex._device_ms_per_mb = 10.0
        ex._drain_floor_ms = 5.0
        for _ in range(40):
            ex.process(img, plan)
        assert ex.stats.spilled == 40
        # 20 count slots: the first ships (never probed before), the other
        # 19 are cheap+stale -> blocked, and they must NOT accumulate into
        # the escape (19 > 16 would ship a second, ungated, shadow)
        assert ex.stats.shadow_probes == 1
        # skipped==0 proves the ship rode the CHEAP path (budget+warmth
        # open) — an escape-path ship would leave a nonzero residue
        assert ex._probe_slots_skipped == 0
    finally:
        ex.shutdown()


def test_host_occupancy_backpressures_spill(img):
    """The host side of the placement comparison includes the pool's
    owed-megapixel backlog (mirroring the device's owed_mb ledger): a
    saturated host pool must push new arrivals back toward the device
    instead of convoying them behind each other — the r5 p99 signature."""
    ex = Executor(ExecutorConfig(host_spill=True, probe_interval=10**9))
    try:
        from imaginary_tpu.engine.executor import _Item

        o = ImageOptions(width=64, height=48)
        item = _Item(img, plan_operation("resize", o, img.shape[0], img.shape[1], 1, 3))
        ex._device_ms_per_mb = 33.0  # tunnel-class link: spill preferred...
        ex._host_ms_per_mpix = 8.0
        # a real accelerator (independent silicon): on the cpu-jax test
        # backend the queue term deliberately cancels, so pin the probe
        ex._device_shares_cpu = False
        assert ex._should_spill(item)
        # ...until the host pool itself is saturated: with enough owed
        # megapixels in flight, the estimated host wait dominates
        ex._host_owed_mpix = 1000.0 * ex._ncpus
        assert not ex._should_spill(item)
        ex._host_owed_mpix = 0.0
        assert ex._should_spill(item)
    finally:
        ex.shutdown()


def test_spill_books_and_releases_host_occupancy(img):
    ex = Executor(ExecutorConfig(host_spill=True, spill_factor=1.0,
                                 probe_interval=10**9))
    try:
        ex._device_ms_per_mb = 10000.0
        o = ImageOptions(width=64, height=48)
        plan = plan_operation("resize", o, img.shape[0], img.shape[1], 1, 3)
        ex.process(img, plan)
        assert ex.stats.spilled == 1
        # the ledger balances after completion and the gauges surface it
        assert ex._host_inflight == 0
        assert ex._host_owed_mpix == 0.0
        d = ex.stats.to_dict()
        assert d["host_inflight"] == 0
        assert d["host_owed_mpix"] == 0.0
        assert "host_spill_p50_ms" in d and "host_spill_p99_ms" in d
    finally:
        ex.shutdown()


def test_force_host_pins_placement(img):
    """force_host (the bench's measurement override) routes every
    host-executable plan to the interpreter even when the device is
    unpriced/fast — and books it as a spill."""
    from imaginary_tpu.engine.executor import last_placement, reset_placement

    ex = Executor(ExecutorConfig(force_host=True))
    try:
        o = ImageOptions(width=64, height=48)
        plan = plan_operation("resize", o, img.shape[0], img.shape[1], 1, 3)
        reset_placement()
        out = ex.process(img, plan)
        assert out.shape == (48, 64, 3)
        assert ex.stats.spilled == 1
        assert ex.stats.items == 0
        assert last_placement() == "host"
    finally:
        ex.shutdown()


def test_no_spill_when_device_fast(img):
    from imaginary_tpu.engine.executor import last_placement, reset_placement

    ex = Executor(ExecutorConfig(host_spill=True))
    try:
        ex._device_ms_per_mb = 0.01  # fast PCIe-class link
        o = ImageOptions(width=64, height=48)
        plan = plan_operation("resize", o, img.shape[0], img.shape[1], 1, 3)
        reset_placement()
        out = ex.process(img, plan)
        assert out.shape == (48, 64, 3)
        assert ex.stats.spilled == 0
        assert ex.stats.items == 1
        assert last_placement() == "device"
    finally:
        ex.shutdown()


def test_embed_modes_match_device(img):
    from imaginary_tpu.options import Extend

    small = img[:100, :150]
    for extend in (Extend.MIRROR, Extend.COPY, Extend.WHITE, Extend.BLACK,
                   Extend.BACKGROUND):
        o = ImageOptions(width=300, height=200, embed=True, extend=extend,
                         background=(10, 200, 30))
        o.mark_defined("embed")
        plan = plan_operation("resize", o, 100, 150, 1, 3)
        hy = host_exec.run(small, plan)
        dy = chain.run_single(small, plan)
        assert hy.shape == dy.shape
        assert _psnr(hy, dy) > 28.0, extend


def test_watermark_composite_matches_device(img):
    o = ImageOptions(width=200, text="hello tpu", opacity=0.7)
    plan = plan_operation("watermark", o, img.shape[0], img.shape[1], 1, 3)
    if not host_exec.can_execute(plan):
        pytest.skip("composite not host-executable")
    hy = host_exec.run(img, plan)
    dy = chain.run_single(img, plan)
    assert hy.shape == dy.shape
    assert _psnr(hy, dy) > 25.0
