"""Driver-contract tests: entry() must jit-compile and dryrun_multichip must
execute a sharded step on the 8-device CPU mesh."""

import importlib.util
import os

import jax


def _load():
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("graft_entry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_entry_jits():
    mod = _load()
    fn, args = mod.entry()
    out, h, w = jax.jit(fn)(*args)
    assert out.shape[0] == args[0].shape[0]
    assert out.dtype.name == "uint8"


def test_dryrun_multichip_8():
    mod = _load()
    mod.dryrun_multichip(8)


def test_dryrun_multichip_odd():
    mod = _load()
    mod.dryrun_multichip(1)
