"""Driver-contract tests: entry() must jit-compile and dryrun_multichip must
execute a sharded step on the 8-device CPU mesh."""

import importlib.util
import os

import jax


def _load():
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("graft_entry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_entry_jits():
    mod = _load()
    fn, args = mod.entry()
    out, h, w = jax.jit(fn)(*args)
    assert out.shape[0] == args[0].shape[0]
    assert out.dtype.name == "uint8"


def test_dryrun_child_env():
    """Unit-level coverage of the child-env construction (seconds, not the
    ~2 min subprocess dryruns below): this is where the round-1 tunnel
    hang would regress."""
    mod = _load()
    base = {
        "XLA_FLAGS": "--foo=1 --xla_force_host_platform_device_count=8 --bar=2",
        "JAX_PLATFORMS": "axon,cpu",
        "PATH": "/usr/bin",
    }
    env = mod._dryrun_child_env(4, base)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["_ITPU_DRYRUN_CHILD"] == "1"
    # the stale count flag is REPLACED, not appended after
    assert env["XLA_FLAGS"].count("xla_force_host_platform_device_count") == 1
    assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]
    assert "--foo=1" in env["XLA_FLAGS"] and "--bar=2" in env["XLA_FLAGS"]
    assert env["PATH"] == "/usr/bin"  # everything else passes through
    assert base["JAX_PLATFORMS"] == "axon,cpu"  # caller env untouched
    # no pre-existing XLA_FLAGS at all
    env2 = mod._dryrun_child_env(8, {})
    assert env2["XLA_FLAGS"] == "--xla_force_host_platform_device_count=8"


def test_dryrun_multichip_8():
    mod = _load()
    mod.dryrun_multichip(8)


def test_dryrun_multichip_odd():
    mod = _load()
    mod.dryrun_multichip(1)
