"""Pallas fused-resample kernel vs the einsum sampling-matrix path
(interpret mode on CPU; the real TPU lowering shares the same trace)."""

import numpy as np
import pytest

import jax.numpy as jnp

from imaginary_tpu.ops.pallas_kernels import resample_2d, resample_rows
from imaginary_tpu.ops.stages import SampleSpec


@pytest.mark.parametrize("kind", ["lanczos3", "linear", "cubic", "nearest"])
def test_resample_rows_matches_einsum(kind):
    rng = np.random.default_rng(0)
    b, in_h, w, c = 2, 64, 32, 3
    out_h = 32
    x = rng.uniform(0, 255, (b, in_h, w, c)).astype(np.float32)
    src = np.array([60.0, 48.0], np.float32)   # dynamic valid sizes
    dst = np.array([30.0, 24.0], np.float32)

    got = np.asarray(resample_rows(jnp.asarray(x), jnp.asarray(src),
                                   jnp.asarray(dst), out_h, kind, interpret=True))

    from imaginary_tpu.ops.stages import sample_matrix

    wts = sample_matrix(out_h, in_h, jnp.asarray(src), jnp.asarray(dst), kind)
    ref = np.asarray(jnp.einsum("byk,bkwc->bywc", wts, jnp.asarray(x)))
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_resample_2d_matches_samplespec():
    rng = np.random.default_rng(1)
    b = 2
    x = rng.uniform(0, 255, (b, 64, 64, 3)).astype(np.float32)
    h = np.array([64, 50], np.int32)
    w = np.array([64, 40], np.int32)
    dst_h = np.array([32.0, 25.0], np.float32)
    dst_w = np.array([32.0, 20.0], np.float32)

    got = np.asarray(
        resample_2d(jnp.asarray(x), h.astype(np.float32), jnp.asarray(dst_h),
                    w.astype(np.float32), jnp.asarray(dst_w), 32, 32,
                    interpret=True)
    )
    ref, _, _ = SampleSpec(32, 32, "lanczos3").apply(
        jnp.asarray(x), jnp.asarray(h), jnp.asarray(w),
        {"dst_h": jnp.asarray(dst_h), "dst_w": jnp.asarray(dst_w)},
    )
    np.testing.assert_allclose(got, np.asarray(ref), atol=1e-3)
