"""Operation dimension/MIME golden tests.

Mirrors the reference's operation tests (image_test.go) on the same fixture
dimensions: imaginary.jpg is 550x740. PIL is the independent oracle for
output size and format, as bimg.NewImage(buf).Size() is upstream
(server_test.go:424-433).
"""

import io
import json

import numpy as np
import pytest
from PIL import Image

from imaginary_tpu.errors import ImageError
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.params import build_params_from_query, parse_json_operations
from imaginary_tpu.pipeline import process_operation, process_pipeline
from tests.conftest import fixture_bytes


def oracle(img_bytes):
    im = Image.open(io.BytesIO(img_bytes))
    return im.width, im.height, (im.format or "").lower()


@pytest.fixture(scope="module")
def jpg(testdata):
    return fixture_bytes("imaginary.jpg")


class TestResize:
    def test_width_and_height(self, jpg):
        out = process_operation("resize", jpg, ImageOptions(width=300, height=300))
        assert out.mime == "image/jpeg"
        assert oracle(out.body)[:2] == (300, 300)

    def test_width_only(self, jpg):
        out = process_operation("resize", jpg, ImageOptions(width=300))
        # 550x740 -> 300x404 (image_test.go:37)
        assert oracle(out.body)[:2] == (300, 404)

    def test_width_nocrop_false(self, jpg):
        o = ImageOptions(width=300, no_crop=False)
        o.mark_defined("no_crop")
        out = process_operation("resize", jpg, o)
        # crop path keeps original height (image_test.go:54)
        assert oracle(out.body)[:2] == (300, 740)

    def test_width_nocrop_true(self, jpg):
        o = ImageOptions(width=300, no_crop=True)
        o.mark_defined("no_crop")
        out = process_operation("resize", jpg, o)
        assert oracle(out.body)[:2] == (300, 404)

    def test_missing_params(self, jpg):
        with pytest.raises(ImageError) as e:
            process_operation("resize", jpg, ImageOptions())
        assert e.value.http_code() == 400


class TestFit:
    def test_fit(self, jpg):
        out = process_operation("fit", jpg, ImageOptions(width=300, height=300))
        # 550x740 -> 223x300 (image_test.go:88)
        assert oracle(out.body)[:2] == (223, 300)

    def test_fit_requires_both(self, jpg):
        with pytest.raises(ImageError):
            process_operation("fit", jpg, ImageOptions(width=300))


class TestCropFamily:
    def test_crop(self, jpg):
        out = process_operation("crop", jpg, ImageOptions(width=200, height=120))
        assert oracle(out.body)[:2] == (200, 120)

    def test_crop_upscale_clamped(self, jpg):
        # crop larger than source without enlarge: window clamps to source
        out = process_operation("crop", jpg, ImageOptions(width=2000, height=100))
        assert oracle(out.body)[:2] == (550, 100)

    def test_enlarge(self, jpg):
        out = process_operation("enlarge", jpg, ImageOptions(width=1100, height=1480))
        assert oracle(out.body)[:2] == (1100, 1480)

    def test_extract(self, jpg):
        out = process_operation(
            "extract", jpg, ImageOptions(top=10, left=10, area_width=200, area_height=120)
        )
        assert oracle(out.body)[:2] == (200, 120)

    def test_extract_out_of_bounds(self, jpg):
        with pytest.raises(ImageError):
            process_operation(
                "extract", jpg, ImageOptions(top=700, left=0, area_width=200, area_height=120)
            )

    def test_smartcrop(self, testdata):
        buf = fixture_bytes("smart-crop.jpg")
        out = process_operation("smartcrop", buf, ImageOptions(width=200, height=150))
        assert oracle(out.body)[:2] == (200, 150)

    def test_smartcrop_finds_salient_region(self, testdata):
        # fixture: flat 230-gray background, red disc centred at (600, 180)
        buf = fixture_bytes("smart-crop.jpg")
        out = process_operation("smartcrop", buf, ImageOptions(width=200, height=150))
        arr = np.asarray(Image.open(io.BytesIO(out.body)).convert("RGB"), dtype=np.float64)
        # the crop must contain the red disc: strong red dominance somewhere
        red_excess = (arr[..., 0] - arr[..., 1]).max()
        assert red_excess > 100, "smartcrop missed the salient red disc"


class TestRotateFlip:
    def test_rotate_90_swaps_dims(self, jpg):
        out = process_operation("rotate", jpg, ImageOptions(rotate=90))
        assert oracle(out.body)[:2] == (740, 550)

    def test_rotate_180_keeps_dims(self, jpg):
        out = process_operation("rotate", jpg, ImageOptions(rotate=180))
        assert oracle(out.body)[:2] == (550, 740)

    def test_rotate_requires_param(self, jpg):
        with pytest.raises(ImageError):
            process_operation("rotate", jpg, ImageOptions())

    def test_flip_flop_pixels(self, jpg):
        src = np.asarray(Image.open(io.BytesIO(jpg)).convert("RGB"))
        flipped = process_operation("flip", jpg, ImageOptions())
        arr = np.asarray(Image.open(io.BytesIO(flipped.body)).convert("RGB"))
        assert arr.shape == src.shape
        # top row of flip ~ bottom row of src (JPEG tolerance)
        assert np.mean(np.abs(arr[0].astype(int) - src[-1].astype(int))) < 20
        flopped = process_operation("flop", jpg, ImageOptions())
        arr2 = np.asarray(Image.open(io.BytesIO(flopped.body)).convert("RGB"))
        assert np.mean(np.abs(arr2[:, 0].astype(int) - src[:, -1].astype(int))) < 20

    def test_autorotate(self, testdata):
        buf = fixture_bytes("exif-orient-6.jpg")
        out = process_operation("autorotate", buf, ImageOptions())
        # 400x300 sensor data, orientation 6 -> upright 300x400
        assert oracle(out.body)[:2] == (300, 400)

    def test_resize_applies_exif(self, testdata):
        buf = fixture_bytes("exif-orient-6.jpg")
        out = process_operation("resize", buf, ImageOptions(width=150))
        # upright 300x400 resized to width 150 -> 150x200
        assert oracle(out.body)[:2] == (150, 200)


class TestConvertThumbnailZoom:
    def test_convert_webp(self, jpg):
        out = process_operation("convert", jpg, ImageOptions(type="webp"))
        assert out.mime == "image/webp"
        assert oracle(out.body)[2] == "webp"

    def test_convert_png(self, jpg):
        out = process_operation("convert", jpg, ImageOptions(type="png"))
        assert out.mime == "image/png"

    def test_convert_requires_type(self, jpg):
        with pytest.raises(ImageError):
            process_operation("convert", jpg, ImageOptions())

    def test_convert_invalid_type(self, jpg):
        with pytest.raises(ImageError):
            process_operation("convert", jpg, ImageOptions(type="bogus"))

    def test_thumbnail(self, jpg):
        out = process_operation("thumbnail", jpg, ImageOptions(width=100))
        assert oracle(out.body)[:2] == (100, 135)  # 740*100/550 = 134.5 -> 135

    def test_zoom(self, jpg):
        out = process_operation("zoom", jpg, ImageOptions(factor=2, width=100))
        # resize to 100x135 then 2x replication
        assert oracle(out.body)[:2] == (200, 270)

    def test_zoom_requires_factor(self, jpg):
        with pytest.raises(ImageError):
            process_operation("zoom", jpg, ImageOptions())


class TestBlurWatermark:
    def test_blur_dims_and_effect(self, jpg):
        # PNG output so the high-frequency check is not polluted by JPEG noise
        out = process_operation("blur", jpg, ImageOptions(sigma=8, type="png"))
        assert oracle(out.body)[:2] == (550, 740)
        src = np.asarray(Image.open(io.BytesIO(jpg)).convert("RGB"), dtype=np.float64)
        blr = np.asarray(Image.open(io.BytesIO(out.body)).convert("RGB"), dtype=np.float64)
        # independent oracle: scipy gaussian with edge-clamp semantics
        from scipy.ndimage import gaussian_filter

        ref = gaussian_filter(src, sigma=(8, 8, 0), mode="nearest")
        assert np.abs(blr - ref).mean() < 2.0

    def test_blur_requires_sigma(self, jpg):
        with pytest.raises(ImageError):
            process_operation("blur", jpg, ImageOptions())

    def test_watermark_text(self, jpg):
        out = process_operation(
            "watermark", jpg, ImageOptions(text="hello", opacity=0.9)
        )
        assert oracle(out.body)[:2] == (550, 740)

    def test_watermark_requires_text(self, jpg):
        with pytest.raises(ImageError):
            process_operation("watermark", jpg, ImageOptions())

    def test_watermark_image(self, jpg, testdata):
        wm = np.zeros((40, 60, 4), dtype=np.uint8)
        wm[..., 1] = 255
        wm[..., 3] = 255
        out = process_operation(
            "watermarkImage", jpg,
            ImageOptions(image="http://example.com/wm.png", top=5, left=5, opacity=1.0),
            watermark_fetcher=lambda url: wm,
        )
        assert oracle(out.body)[:2] == (550, 740)
        arr = np.asarray(Image.open(io.BytesIO(out.body)).convert("RGB"))
        patch = arr[10:40, 10:60]
        assert patch[..., 1].mean() > 200  # green overlay landed

    def test_watermark_image_requires_url(self, jpg):
        with pytest.raises(ImageError):
            process_operation("watermarkImage", jpg, ImageOptions())


class TestInfo:
    def test_info(self, jpg):
        out = process_operation("info", jpg, ImageOptions())
        assert out.mime == "application/json"
        meta = json.loads(out.body)
        assert meta["width"] == 550 and meta["height"] == 740
        assert meta["type"] == "jpeg"


class TestPipeline:
    def test_crop_then_convert(self, jpg):
        ops = parse_json_operations(
            '[{"operation": "crop", "params": {"width": 300, "height": 260}},'
            ' {"operation": "convert", "params": {"type": "webp"}}]'
        )
        out = process_pipeline(jpg, ImageOptions(operations=ops))
        # image_test.go:109-142: 300x260 webp
        w, h, fmt = oracle(out.body)
        assert (w, h, fmt) == (300, 260, "webp")

    def test_pipeline_fused_chain(self, jpg):
        ops = parse_json_operations(
            '[{"operation": "resize", "params": {"width": 400}},'
            ' {"operation": "blur", "params": {"sigma": 3}},'
            ' {"operation": "crop", "params": {"width": 200, "height": 150}}]'
        )
        out = process_pipeline(jpg, ImageOptions(operations=ops))
        assert oracle(out.body)[:2] == (200, 150)

    def test_pipeline_limit(self, jpg):
        ops = parse_json_operations(
            "[" + ",".join('{"operation": "flip"}' for _ in range(11)) + "]"
        )
        with pytest.raises(ImageError) as e:
            process_pipeline(jpg, ImageOptions(operations=ops))
        assert "Maximum pipeline operations" in e.value.message

    def test_pipeline_unknown_op(self, jpg):
        ops = parse_json_operations('[{"operation": "bogus"}]')
        with pytest.raises(ImageError):
            process_pipeline(jpg, ImageOptions(operations=ops))

    def test_pipeline_ignore_failure(self, jpg):
        ops = parse_json_operations(
            '[{"operation": "resize", "ignore_failure": true, "params": {}},'
            ' {"operation": "crop", "params": {"width": 120, "height": 90}}]'
        )
        out = process_pipeline(jpg, ImageOptions(operations=ops))
        assert oracle(out.body)[:2] == (120, 90)

    def test_pipeline_empty(self, jpg):
        with pytest.raises(ImageError):
            process_pipeline(jpg, ImageOptions())


class TestQualityAndFormats:
    def test_resize_png_roundtrip(self, testdata):
        buf = fixture_bytes("test.png")
        out = process_operation("resize", buf, ImageOptions(width=100))
        w, h, fmt = oracle(out.body)
        assert (w, h, fmt) == (100, 100, "png")

    def test_resize_content_sane(self, jpg):
        """Downscale must look like the source (correlation check)."""
        out = process_operation("resize", jpg, ImageOptions(width=128, height=128, force=True))
        got = np.asarray(Image.open(io.BytesIO(out.body)).convert("RGB"), dtype=np.float64)
        ref = np.asarray(
            Image.open(io.BytesIO(jpg)).convert("RGB").resize((128, 128), Image.LANCZOS),
            dtype=np.float64,
        )
        err = np.abs(got - ref).mean()
        assert err < 12.0, f"mean abs err vs PIL lanczos = {err:.2f}"


class TestBucketClampRegressions:
    """Review findings: dynamic_slice whole-window clamping must not shift
    crops/watermarks when actual offset + bucketed size exceeds the input
    bucket (top+eh fits but top+bucket(eh) does not)."""

    def _gradient_jpgless(self, h, w):
        # exact pixel values, encode as PNG to avoid JPEG noise
        import io as _io
        yy = np.arange(h, dtype=np.uint8)[:, None]
        arr = np.repeat(np.repeat(yy, w, axis=1)[..., None], 3, axis=2)
        b = _io.BytesIO()
        Image.fromarray(arr).save(b, "PNG")
        return b.getvalue()

    def test_extract_alignment_at_bucket_boundary(self):
        # 100px tall (bucket 128); extract rows 33..97 -> bucket(65)=96;
        # 33+96 > 128 would have shifted with dynamic_slice
        buf = self._gradient_jpgless(100, 100)
        out = process_operation(
            "extract", buf,
            ImageOptions(top=33, left=0, area_width=100, area_height=65, type="png"),
        )
        arr = np.asarray(Image.open(io.BytesIO(out.body)).convert("RGB"))
        assert arr.shape[:2] == (65, 100)
        assert arr[0, 0, 0] == 33 and arr[-1, 0, 0] == 97

    def test_watermark_image_position_at_bucket_boundary(self):
        buf = self._gradient_jpgless(100, 100)
        wm = np.zeros((65, 65, 4), dtype=np.uint8)
        wm[..., 0] = 255
        wm[..., 3] = 255
        out = process_operation(
            "watermarkImage", buf,
            ImageOptions(image="u", top=35, left=35, opacity=1.0, type="png"),
            watermark_fetcher=lambda u: wm,
        )
        arr = np.asarray(Image.open(io.BytesIO(out.body)).convert("RGB"))
        # row 34 untouched, row 35 red; block spans rows/cols 35..99
        assert arr[34, 40, 0] == 34
        assert arr[35, 40, 0] == 255
        assert arr[40, 34, 0] == 40  # left of block: untouched
        assert arr[99, 99, 0] == 255  # block corner covered

    def test_zoom_negative_factor_rejected(self):
        buf = self._gradient_jpgless(50, 50)
        from imaginary_tpu.params import build_params_from_operation
        from imaginary_tpu.options import PipelineOperation
        o = build_params_from_operation(PipelineOperation(name="zoom", params={"factor": -2}))
        with pytest.raises(ImageError):
            process_operation("zoom", buf, o)


class TestOutputBucketTightening:
    """Final-stage buckets round to snug mult-of-16 dims: device->host
    readback bytes, not the geometric input ladder, bound throughput."""

    def test_tight_dim_ladder(self):
        from imaginary_tpu.ops.buckets import bucket_dim, tight_dim

        assert tight_dim(200) == 208
        assert tight_dim(300) == 304
        assert tight_dim(512) == 512
        assert tight_dim(513) == 544
        assert tight_dim(2000) == 2048
        for n in (1, 17, 99, 511, 1025, 4000):
            assert n <= tight_dim(n) <= bucket_dim(n)

    def test_final_sample_stage_retargeted(self):
        from imaginary_tpu.ops.plan import plan_operation

        plan = plan_operation("resize", ImageOptions(width=300, height=200), 1080, 1920, 0, 3)
        last_shape = [s.spec for s in plan.stages if hasattr(s.spec, "out_hb")][-1]
        assert (last_shape.out_hb, last_shape.out_wb) == (208, 304)

    def test_shape_preserving_chain_gets_slice_stage(self):
        from imaginary_tpu.ops.plan import plan_operation
        from imaginary_tpu.ops.stages import ShrinkBucketSpec

        # flip keeps 1080p dims: ladder pad (1280, 2048) -> tight (1088, 1920)
        plan = plan_operation("flip", ImageOptions(), 1080, 1920, 0, 3)
        assert isinstance(plan.stages[-1].spec, ShrinkBucketSpec)
        assert (plan.stages[-1].spec.out_hb, plan.stages[-1].spec.out_wb) == (1088, 1920)

    def test_tightened_chain_still_correct(self, jpg):
        out = process_operation("resize", jpg, ImageOptions(width=300, height=200))
        assert oracle(out.body)[:2] == (300, 200)


class TestFontResolution:
    """Pango-style font specs resolve to real truetype files
    (ref: image.go:328-338 renders via pango; VERDICT r1 weak #5)."""

    def test_bold_spec_changes_rendering(self):
        import numpy as np

        from imaginary_tpu.ops.text import _font_index, rasterize_text

        if not _font_index():
            import pytest

            pytest.skip("no ttf fonts on host (bitmap fallback has no bold)")

        a = rasterize_text("Hello World", "sans 16", 72, 400, (255, 0, 0), 600, 400)
        b = rasterize_text("Hello World", "sans bold 16", 72, 400, (255, 0, 0), 600, 400)
        # bold must visibly differ (wider glyphs or different coverage)
        if a.shape == b.shape:
            assert not np.array_equal(a, b)
        else:
            assert b.shape[1] >= a.shape[1]

    def test_family_resolution(self):
        from imaginary_tpu.ops.text import _parse_font_spec, _resolve_font_path

        fam, bold, italic, size = _parse_font_spec("sans bold 16")
        assert (fam, bold, size) == (["sans"], True, 16.0)
        path = _resolve_font_path(fam, bold, italic)
        assert path is None or path.endswith(".ttf")

    def test_truetype_used_when_available(self):
        from PIL import ImageFont

        from imaginary_tpu.ops.text import _font_index, _load_font

        if not _font_index():
            import pytest

            pytest.skip("no ttf fonts on host")
        f = _load_font("sans 14", 72)
        assert isinstance(f, ImageFont.FreeTypeFont)


class TestRotateAngleFlooring:
    """bimg floors arbitrary angles to the lower 90 multiple
    (calculateRotationAngle); rotate=135 must turn the image, not no-op."""

    @pytest.mark.parametrize("angle,expect_wh", [
        (45, (550, 740)),    # floors to 0: identity
        (135, (740, 550)),   # floors to 90
        (225, (550, 740)),   # floors to 180
        (275, (740, 550)),   # floors to 270
        (450, (740, 550)),   # >=360: getAngle clamps min(angle, 270) -> 270
    ])
    def test_floors_like_bimg(self, angle, expect_wh):
        o = ImageOptions(rotate=angle)
        o.mark_defined("rotate")
        out = process_operation("rotate", fixture_bytes("imaginary.jpg"), o)
        im = Image.open(io.BytesIO(out.body))
        assert im.size == expect_wh

    def test_negative_rotate_via_pipeline_json_noops(self):
        """Negatives reach the planner only through pipeline JSON (the
        query layer abs()es); every plausible bimg reading no-ops them."""
        ops = json.dumps([
            {"operation": "rotate", "params": {"rotate": -90}},
            {"operation": "convert", "params": {"type": "png"}},
        ])
        o = build_params_from_query({"operations": ops})
        from imaginary_tpu.pipeline import process_pipeline

        out = process_pipeline(fixture_bytes("imaginary.jpg"), o)
        assert Image.open(io.BytesIO(out.body)).size == (550, 740)  # unrotated
