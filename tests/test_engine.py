"""Micro-batch executor tests: batching behavior, correctness under
concurrency, and mesh-sharded dispatch on the 8-device CPU mesh."""

import threading

import numpy as np
import pytest

from imaginary_tpu.engine import Executor, ExecutorConfig
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.ops.plan import plan_operation


def _img(h, w, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (h, w, 3), dtype=np.uint8)


def _resize_plan(h, w, width):
    return plan_operation("resize", ImageOptions(width=width), h, w, 0, 3)


class TestExecutor:
    def test_single_item(self):
        ex = Executor(ExecutorConfig(window_ms=1))
        out = ex.process(_img(100, 80), _resize_plan(100, 80, 40))
        assert out.shape == (50, 40, 3)
        ex.shutdown()

    def test_identity_plan_short_circuits(self):
        ex = Executor(ExecutorConfig(window_ms=1))
        arr = _img(64, 64)
        plan = plan_operation("autorotate", ImageOptions(), 64, 64, 0, 3)
        out = ex.process(arr, plan)
        assert out is arr
        assert ex.stats.batches == 0
        ex.shutdown()

    def test_same_signature_items_batch_together(self):
        ex = Executor(ExecutorConfig(window_ms=30, max_batch=8))
        futs = [
            ex.submit(_img(100, 80, seed=i), _resize_plan(100, 80, 40))
            for i in range(6)
        ]
        outs = [f.result(timeout=120) for f in futs]
        assert all(o.shape == (50, 40, 3) for o in outs)
        # all six shared one device dispatch
        assert ex.stats.batches == 1
        assert ex.stats.max_group_seen == 6
        # different seeds -> different outputs (no cross-item mixing)
        assert not np.array_equal(outs[0], outs[1])
        ex.shutdown()

    def test_mixed_signatures_batch_separately(self):
        ex = Executor(ExecutorConfig(window_ms=30, max_batch=8))
        f1 = [ex.submit(_img(100, 80, seed=i), _resize_plan(100, 80, 40)) for i in range(3)]
        f2 = [ex.submit(_img(300, 200, seed=i), _resize_plan(300, 200, 64)) for i in range(3)]
        for f in f1 + f2:
            f.result(timeout=120)
        assert ex.stats.batches == 2
        ex.shutdown()

    def test_error_propagates_to_future(self, monkeypatch):
        """A dispatch failure that exhausts EVERY fault domain surfaces
        the real device error to the caller (a single-device transient
        failure now fails over to another chip instead — pinned by
        test_devhealth's failover tests)."""
        import jax

        from imaginary_tpu.engine import executor as executor_mod

        ex = Executor(ExecutorConfig(window_ms=1))
        plan = _resize_plan(100, 80, 40)
        real = executor_mod.chain_mod.launch_batch
        n_dev = len(jax.local_devices())
        calls = {"n": 0}

        def flaky(*a, **k):
            calls["n"] += 1
            if calls["n"] <= n_dev:
                raise RuntimeError("device fell over")
            return real(*a, **k)

        monkeypatch.setattr(executor_mod.chain_mod, "launch_batch", flaky)
        with pytest.raises(RuntimeError, match="device fell over"):
            ex.process(_img(100, 80), plan)
        # executor survives and keeps serving
        out = ex.process(_img(100, 80), plan)
        assert out.shape == (50, 40, 3)
        ex.shutdown()

    def test_concurrent_submitters(self):
        ex = Executor(ExecutorConfig(window_ms=5, max_batch=8))
        results = {}

        def worker(i):
            out = ex.process(_img(100, 80, seed=i), _resize_plan(100, 80, 40))
            results[i] = out.shape

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(results) == 16
        assert all(s == (50, 40, 3) for s in results.values())
        assert ex.stats.items == 16
        ex.shutdown()

    def test_stats_dict(self):
        ex = Executor(ExecutorConfig(window_ms=1))
        ex.process(_img(64, 64), _resize_plan(64, 64, 32))
        d = ex.stats.to_dict()
        assert d["items"] == 1 and d["batches"] == 1
        assert d["compile_cache_size"] >= 1
        ex.shutdown()


class TestMeshExecutor:
    """Sharded dispatch over the 8-device CPU mesh (conftest forces
    xla_force_host_platform_device_count=8)."""

    def test_mesh_available(self):
        import jax

        assert len(jax.devices()) == 8

    def test_sharded_batch_correctness(self):
        ex = Executor(ExecutorConfig(window_ms=30, max_batch=8, use_mesh=True))
        futs = [
            ex.submit(_img(100, 80, seed=i), _resize_plan(100, 80, 40))
            for i in range(8)
        ]
        outs = [f.result(timeout=180) for f in futs]
        assert all(o.shape == (50, 40, 3) for o in outs)
        # compare against the unsharded path
        ref_ex = Executor(ExecutorConfig(window_ms=1))
        ref = ref_ex.process(_img(100, 80, seed=3), _resize_plan(100, 80, 40))
        assert np.array_equal(outs[3], ref)
        ex.shutdown()
        ref_ex.shutdown()

    def test_sharded_batch_pads_to_mesh(self):
        # 5 items on an 8-wide batch axis: executor pads internally
        ex = Executor(ExecutorConfig(window_ms=30, max_batch=8, use_mesh=True))
        futs = [
            ex.submit(_img(64, 64, seed=i), _resize_plan(64, 64, 32)) for i in range(5)
        ]
        outs = [f.result(timeout=180) for f in futs]
        assert all(o.shape == (32, 32, 3) for o in outs)
        assert ex.stats.items == 5
        ex.shutdown()


class TestSpillPolicy:
    def test_spill_error_falls_through_to_device(self, monkeypatch):
        """A host-interpreter failure must not fail the request: the item
        re-routes to the device queue (ADVICE r1 medium #2)."""
        from imaginary_tpu.engine import executor as ex_mod

        ex = Executor(ExecutorConfig(window_ms=1, probe_interval=10**9, host_spill=True))
        # force the cost model into "spill everything" territory
        ex._device_ms_per_mb = 10000.0
        ex._host_ms_per_mpix = 0.01
        monkeypatch.setattr(
            ex_mod.host_exec, "run",
            lambda arr, plan: (_ for _ in ()).throw(RuntimeError("edge case")),
        )
        out = ex.process(_img(100, 80), _resize_plan(100, 80, 40))
        assert out.shape == (50, 40, 3)
        assert ex.stats.spill_errors == 1
        assert ex.stats.spilled == 0  # failed spill is not a successful spill
        ex.shutdown()

    def test_successful_spill_counts(self):
        ex = Executor(ExecutorConfig(window_ms=1, probe_interval=10**9, host_spill=True))
        ex._device_ms_per_mb = 10000.0
        ex._host_ms_per_mpix = 0.01
        out = ex.process(_img(100, 80), _resize_plan(100, 80, 40))
        assert out.shape == (50, 40, 3)
        assert ex.stats.spilled == 1
        assert ex.stats.spill_errors == 0
        ex.shutdown()

    def test_cold_compile_does_not_seed_cost_model(self):
        """The first drain of a never-seen chain signature pays XLA compile;
        that sample must not enter device_ms_per_mb (ADVICE r1 medium #1)."""
        from imaginary_tpu.ops import chain as chain_mod

        chain_mod.clear_cache()
        ex = Executor(ExecutorConfig(window_ms=1))
        ex.process(_img(100, 80), _resize_plan(100, 80, 40))
        # give the fetcher a beat to finish booking the drain
        import time as _t

        for _ in range(100):
            if ex.stats.groups >= 1:
                break
            _t.sleep(0.01)
        assert ex._device_ms_per_mb is None  # cold drain excluded
        # a second, warm drain seeds it
        ex.process(_img(100, 80, seed=1), _resize_plan(100, 80, 40))
        for _ in range(100):
            if ex._device_ms_per_mb is not None:
                break
            _t.sleep(0.01)
        assert ex._device_ms_per_mb is not None
        ex.shutdown()


class TestStageTimes:
    def test_executor_records_stage_times(self):
        from imaginary_tpu.engine.timing import TIMES

        TIMES.reset()
        # host_spill off: the test pins DEVICE-path stage metrics, and with
        # the drain-floor term a priced link correctly spills tiny items
        ex = Executor(ExecutorConfig(window_ms=1, host_spill=False))
        ex.process(_img(100, 80), _resize_plan(100, 80, 40))
        ex.process(_img(100, 80, seed=1), _resize_plan(100, 80, 40))
        snap = TIMES.snapshot()
        assert snap["queue_wait"]["count"] == 2
        # warm (non-cold) drains record the merged drain cost
        assert "drain" in snap
        assert snap["drain"]["mean_ms"] >= 0.0
        ex.shutdown()

    def test_split_drain_timing_records_device_wait_and_d2h(self):
        from imaginary_tpu.engine.timing import TIMES

        TIMES.reset()
        ex = Executor(ExecutorConfig(window_ms=1, split_drain_timing=True,
                                     host_spill=False))
        ex.process(_img(100, 80), _resize_plan(100, 80, 40))
        ex.process(_img(100, 80, seed=1), _resize_plan(100, 80, 40))
        snap = TIMES.snapshot()
        assert "device_wait" in snap and "d2h" in snap
        assert snap["device_wait"]["mean_ms"] >= 0.0
        ex.shutdown()


class TestBatchLadderUnification:
    """One source of truth for max_batch across CLI / web config / executor,
    and a prewarm ladder that provably covers every formable batch size
    (VERDICT r3 weak #5)."""

    def test_defaults_agree_everywhere(self):
        from imaginary_tpu.cli import build_parser
        from imaginary_tpu.engine.executor import MAX_BATCH, ExecutorConfig
        from imaginary_tpu.web.config import ServerOptions

        assert ExecutorConfig().max_batch == MAX_BATCH
        assert ServerOptions().max_batch == MAX_BATCH
        args = build_parser().parse_args([])
        assert args.max_batch == MAX_BATCH
        # spatial threshold: kept literal in the import-light config/CLI
        # modules (jax must not load for --help); this pin is the single
        # source of truth across the three definitions
        assert (
            ExecutorConfig().spatial_threshold_px
            == ServerOptions().spatial_threshold_px
            == args.spatial_threshold_px
        )

    def test_batch_ladder_covers_padding(self):
        from imaginary_tpu.engine.executor import batch_ladder

        assert batch_ladder(16) == (1, 2, 4, 8, 16)
        # a non-power-of-two cap still pads up to the next power of two
        assert batch_ladder(12) == (1, 2, 4, 8, 16)
        assert batch_ladder(1) == (1,)

    def test_no_compile_after_prewarm_at_any_formable_batch(self):
        from imaginary_tpu.engine.executor import MAX_BATCH, batch_ladder
        from imaginary_tpu.ops import chain as chain_mod

        arr = _img(100, 80)
        plan = _resize_plan(100, 80, 40)
        # prewarm exactly the ladder the default deployment prewarm uses
        for b in batch_ladder():
            chain_mod.run_batch([arr] * b, [plan] * b)
        warmed = chain_mod.cache_size()
        # every group size the executor can form must hit the warm cache
        ex = Executor(ExecutorConfig(window_ms=5))
        for n in range(1, MAX_BATCH + 1):
            futs = [ex.submit(_img(100, 80, seed=i), plan) for i in range(n)]
            for f in futs:
                f.result(timeout=120)
        assert chain_mod.cache_size() == warmed
        ex.shutdown()


class TestSpatialServing:
    """Spatial (W-axis) sharding on the serving path (VERDICT r1 next #6):
    large buckets route through the (batch x spatial) mesh; output must be
    bit-identical to unsharded execution."""

    def test_large_bucket_routes_spatially_and_matches(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        arr = _img(256, 512, seed=3)
        plan = plan_operation(
            "resize", ImageOptions(width=128, sigma=1.2), 256, 512, 0, 3
        )
        ex_sp = Executor(ExecutorConfig(
            window_ms=1, use_mesh=True, spatial=2, spatial_threshold_px=1,
        ))
        out_sp = ex_sp.process(arr, plan)
        assert ex_sp.stats.spatial_batches >= 1
        ex_sp.shutdown()

        ex_plain = Executor(ExecutorConfig(window_ms=1))
        out_plain = ex_plain.process(arr, plan)
        assert ex_plain.stats.spatial_batches == 0
        ex_plain.shutdown()

        np.testing.assert_array_equal(out_sp, out_plain)

    def test_small_bucket_stays_batch_sharded(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        ex = Executor(ExecutorConfig(window_ms=1, use_mesh=True, spatial=2))
        out = ex.process(_img(100, 80), _resize_plan(100, 80, 40))
        assert out.shape == (50, 40, 3)
        assert ex.stats.spatial_batches == 0
        ex.shutdown()

    def test_uneven_spatial_falls_back_to_batch_sharding(self):
        """W not divisible by the spatial axis: device_put would reject the
        sharding, so the dispatcher must fall back to batch-only (review r2)."""
        import jax

        if len(jax.devices()) < 6:
            pytest.skip("needs >= 6 devices")
        ex = Executor(ExecutorConfig(
            window_ms=1, use_mesh=True, n_devices=6, spatial=3,
            spatial_threshold_px=1,
        ))
        # bucket W for a 62-wide image is 64 — not a multiple of 3
        out = ex.process(_img(100, 62), _resize_plan(100, 62, 40))
        assert out.shape == (65, 40, 3)
        assert ex.stats.spatial_batches == 0
        ex.shutdown()
