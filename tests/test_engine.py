"""Micro-batch executor tests: batching behavior, correctness under
concurrency, and mesh-sharded dispatch on the 8-device CPU mesh."""

import threading

import numpy as np
import pytest

from imaginary_tpu.engine import Executor, ExecutorConfig
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.ops.plan import plan_operation


def _img(h, w, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (h, w, 3), dtype=np.uint8)


def _resize_plan(h, w, width):
    return plan_operation("resize", ImageOptions(width=width), h, w, 0, 3)


class TestExecutor:
    def test_single_item(self):
        ex = Executor(ExecutorConfig(window_ms=1))
        out = ex.process(_img(100, 80), _resize_plan(100, 80, 40))
        assert out.shape == (50, 40, 3)
        ex.shutdown()

    def test_identity_plan_short_circuits(self):
        ex = Executor(ExecutorConfig(window_ms=1))
        arr = _img(64, 64)
        plan = plan_operation("autorotate", ImageOptions(), 64, 64, 0, 3)
        out = ex.process(arr, plan)
        assert out is arr
        assert ex.stats.batches == 0
        ex.shutdown()

    def test_same_signature_items_batch_together(self):
        ex = Executor(ExecutorConfig(window_ms=30, max_batch=8))
        futs = [
            ex.submit(_img(100, 80, seed=i), _resize_plan(100, 80, 40))
            for i in range(6)
        ]
        outs = [f.result(timeout=120) for f in futs]
        assert all(o.shape == (50, 40, 3) for o in outs)
        # all six shared one device dispatch
        assert ex.stats.batches == 1
        assert ex.stats.max_group_seen == 6
        # different seeds -> different outputs (no cross-item mixing)
        assert not np.array_equal(outs[0], outs[1])
        ex.shutdown()

    def test_mixed_signatures_batch_separately(self):
        ex = Executor(ExecutorConfig(window_ms=30, max_batch=8))
        f1 = [ex.submit(_img(100, 80, seed=i), _resize_plan(100, 80, 40)) for i in range(3)]
        f2 = [ex.submit(_img(300, 200, seed=i), _resize_plan(300, 200, 64)) for i in range(3)]
        for f in f1 + f2:
            f.result(timeout=120)
        assert ex.stats.batches == 2
        ex.shutdown()

    def test_error_propagates_to_future(self, monkeypatch):
        from imaginary_tpu.engine import executor as executor_mod

        ex = Executor(ExecutorConfig(window_ms=1))
        plan = _resize_plan(100, 80, 40)
        real = executor_mod.chain_mod.launch_batch
        calls = {"n": 0}

        def flaky(*a, **k):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("device fell over")
            return real(*a, **k)

        monkeypatch.setattr(executor_mod.chain_mod, "launch_batch", flaky)
        with pytest.raises(RuntimeError, match="device fell over"):
            ex.process(_img(100, 80), plan)
        # executor survives and keeps serving
        out = ex.process(_img(100, 80), plan)
        assert out.shape == (50, 40, 3)
        ex.shutdown()

    def test_concurrent_submitters(self):
        ex = Executor(ExecutorConfig(window_ms=5, max_batch=8))
        results = {}

        def worker(i):
            out = ex.process(_img(100, 80, seed=i), _resize_plan(100, 80, 40))
            results[i] = out.shape

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(results) == 16
        assert all(s == (50, 40, 3) for s in results.values())
        assert ex.stats.items == 16
        ex.shutdown()

    def test_stats_dict(self):
        ex = Executor(ExecutorConfig(window_ms=1))
        ex.process(_img(64, 64), _resize_plan(64, 64, 32))
        d = ex.stats.to_dict()
        assert d["items"] == 1 and d["batches"] == 1
        assert d["compile_cache_size"] >= 1
        ex.shutdown()


class TestMeshExecutor:
    """Sharded dispatch over the 8-device CPU mesh (conftest forces
    xla_force_host_platform_device_count=8)."""

    def test_mesh_available(self):
        import jax

        assert len(jax.devices()) == 8

    def test_sharded_batch_correctness(self):
        ex = Executor(ExecutorConfig(window_ms=30, max_batch=8, use_mesh=True))
        futs = [
            ex.submit(_img(100, 80, seed=i), _resize_plan(100, 80, 40))
            for i in range(8)
        ]
        outs = [f.result(timeout=180) for f in futs]
        assert all(o.shape == (50, 40, 3) for o in outs)
        # compare against the unsharded path
        ref_ex = Executor(ExecutorConfig(window_ms=1))
        ref = ref_ex.process(_img(100, 80, seed=3), _resize_plan(100, 80, 40))
        assert np.array_equal(outs[3], ref)
        ex.shutdown()
        ref_ex.shutdown()

    def test_sharded_batch_pads_to_mesh(self):
        # 5 items on an 8-wide batch axis: executor pads internally
        ex = Executor(ExecutorConfig(window_ms=30, max_batch=8, use_mesh=True))
        futs = [
            ex.submit(_img(64, 64, seed=i), _resize_plan(64, 64, 32)) for i in range(5)
        ]
        outs = [f.result(timeout=180) for f in futs]
        assert all(o.shape == (32, 32, 3) for o in outs)
        assert ex.stats.items == 5
        ex.shutdown()
