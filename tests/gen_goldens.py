"""Golden regression outputs for the reference op matrix.

The reference's own tests grade *dimensions* per op (image_test.go:8-142,
assertSize); libvips is not installable in this environment, so true
libvips pixel goldens cannot be produced here. These goldens are the next
strongest thing: the framework's device-path output pixels for the
reference matrix, committed once and graded on every run — they pin the
numerics (any kernel/dtype/default change that moves pixels more than
~1 LSB fails the floor) on top of the exact-dimension parity the
reference asserts. Pixel-accuracy parity against independent oracles
(PIL Lanczos, dense float conv) is test_quality.py's job.

Regenerate deliberately with: python -m tests.gen_goldens
"""

from __future__ import annotations

import io
import json
import os

import numpy as np

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "goldens")

# (name, operation, options-kwargs, expected (w, h) from image_test.go /
# the reference's dimension semantics on the 550x740 fixture)
MATRIX = [
    ("resize_w300", "resize", {"width": 300}, (300, 404)),            # image_test.go:25-38
    ("resize_300x300", "resize", {"width": 300, "height": 300}, (300, 300)),  # :9-23
    ("resize_w300_nocrop", "resize", {"width": 300, "no_crop": True}, (300, 404)),  # :58-74
    ("fit_300x300", "fit", {"width": 300, "height": 300}, (223, 300)),  # :78-94
    ("enlarge_1440x900", "enlarge", {"width": 1440, "height": 900}, (1440, 900)),
    ("extract_100_100_300x150", "extract",
     {"top": 100, "left": 100, "area_width": 300, "area_height": 150}, (300, 150)),
    ("crop_300x260", "crop", {"width": 300, "height": 260}, (300, 260)),  # :110-142
    ("rotate_90", "rotate", {"rotate": 90}, (740, 550)),
    ("flip", "flip", {}, (550, 740)),
    ("thumbnail_100", "thumbnail", {"width": 100}, (100, 135)),  # aspect kept (image.go:279-284)
    ("blur_s5", "blur", {"sigma": 5.0}, (550, 740)),
    ("zoom_2", "zoom",
     {"factor": 2, "top": 80, "left": 80, "area_width": 200, "area_height": 150},
     (400, 300)),
]

SMARTCROP = ("smartcrop_300x260", "smartcrop", {"width": 300, "height": 260},
             (300, 260))

# Multi-op /pipeline chains: pins the COMBINED plan end-to-end across the
# three resample topologies — FUSED (crop whose target aspect matches the
# source plans a pure cover-resize, so crop+resize collapse into ONE
# direct sample: the r4 adjacent-resample fusion), EXTRACT-BLOCKED (crop
# with an aspect-mismatched window keeps Sample->Extract->Sample), and
# SINGLE-SAMPLE (rotate+thumbnail: nothing to fuse). n_samples is
# asserted at generation AND grading time so a fusion regression is
# caught as a plan-shape change, not just pixel drift. Expected dims
# derive from the reference's per-op semantics on the 550x740 fixture.
PIPELINES = [
    ("pipeline_fused_crop_resize",
     [{"operation": "crop", "params": {"width": 440, "height": 592}},
      {"operation": "resize", "params": {"width": 240}},
      {"operation": "blur", "params": {"sigma": 1.5}},
      {"operation": "convert", "params": {"type": "png"}}],
     (240, 323), 1),
    ("pipeline_crop_resize_blur",
     [{"operation": "crop", "params": {"width": 480, "height": 360}},
      {"operation": "resize", "params": {"width": 240}},
      {"operation": "blur", "params": {"sigma": 1.5}},
      {"operation": "convert", "params": {"type": "png"}}],
     (240, 180), 2),
    ("pipeline_rotate_thumbnail",
     [{"operation": "rotate", "params": {"rotate": 90}},
      {"operation": "thumbnail", "params": {"width": 120}},
      {"operation": "convert", "params": {"type": "png"}}],
     (120, 89), 1),
]


def _pipeline_sample_count(ops: list, src_h: int = 740, src_w: int = 550) -> int:
    import json as _json

    from imaginary_tpu.options import ImageOptions
    from imaginary_tpu.params import parse_json_operations
    from imaginary_tpu.pipeline import _build_pipeline_plan
    from imaginary_tpu.ops.stages import SampleSpec

    o = ImageOptions(operations=parse_json_operations(_json.dumps(ops)))
    plan, *_ = _build_pipeline_plan(o, src_h, src_w, 0, 3, None, None)
    return sum(isinstance(st.spec, SampleSpec) for st in plan.stages)


def _run_pipeline_case(buf: bytes, ops: list):
    import json as _json

    from PIL import Image

    from imaginary_tpu.options import ImageOptions
    from imaginary_tpu.params import parse_json_operations
    from imaginary_tpu.pipeline import process_pipeline

    o = ImageOptions(operations=parse_json_operations(_json.dumps(ops)))
    out = process_pipeline(buf, o)
    return np.asarray(Image.open(io.BytesIO(out.body)).convert("RGB"))


def _setup_cpu():
    import jax

    jax.config.update("jax_platforms", "cpu")


def _run_case(buf: bytes, op: str, kw: dict):
    from PIL import Image

    from imaginary_tpu.options import ImageOptions
    from imaginary_tpu.pipeline import process_operation

    defined = [k for k in kw]
    o = ImageOptions(type="png", **kw)  # PNG out: lossless, no JPEG wobble
    for k in defined:
        o.mark_defined(k)
    out = process_operation(op, buf, o)
    arr = np.asarray(Image.open(io.BytesIO(out.body)).convert("RGB"))
    return arr


def _smartcrop_window(buf: bytes, kw: dict) -> dict:
    """(top, left, new_h, new_w) the smartcrop saliency chose — the window
    offsets are computed on device inside SmartExtractSpec, so replay the
    chain eagerly up to that stage and capture smart_offsets' choice.
    Golden-pinned so a saliency change is caught as a window MOVE, not
    just pixel drift."""
    import jax.numpy as jnp

    from imaginary_tpu import codecs
    from imaginary_tpu.options import ImageOptions
    from imaginary_tpu.ops import chain as chain_mod
    from imaginary_tpu.ops.plan import plan_operation
    from imaginary_tpu.ops.saliency import smart_offsets
    from imaginary_tpu.ops.stages import SmartExtractSpec

    o = ImageOptions(**kw)
    for k in kw:
        o.mark_defined(k)
    # decode exactly as the production path does: smartcrop is
    # shrink-on-load-safe, so the window must be pinned on the SAME
    # (possibly 1/N) decode process_operation grades against
    from imaginary_tpu.pipeline import _pick_shrink

    d = codecs.decode(buf, _pick_shrink("smartcrop", buf, o))
    plan = plan_operation("smartcrop", o, d.array.shape[0], d.array.shape[1],
                          d.orientation, d.array.shape[2])
    dyns = chain_mod._stack_dyns([plan])
    x = jnp.asarray(chain_mod.pad_to_bucket(d.array)[None]).astype(jnp.float32)
    h = jnp.array([d.array.shape[0]], jnp.int32)
    w = jnp.array([d.array.shape[1]], jnp.int32)
    for st, dyn in zip(plan.stages, dyns):
        if isinstance(st.spec, SmartExtractSpec):
            top, left = smart_offsets(x, h, w, dyn["new_h"], dyn["new_w"])
            return {
                "top": int(np.asarray(top).ravel()[0]),
                "left": int(np.asarray(left).ravel()[0]),
                "new_h": int(np.asarray(dyn["new_h"]).ravel()[0]),
                "new_w": int(np.asarray(dyn["new_w"]).ravel()[0]),
            }
        x, h, w = st.spec.apply(x, h, w, dyn)
    raise SystemExit("smartcrop plan has no SmartExtractSpec stage")


def generate_all(out_dir: str = GOLDEN_DIR) -> None:
    _setup_cpu()
    from PIL import Image

    os.makedirs(out_dir, exist_ok=True)
    from tests.conftest import fixture_bytes  # regenerates missing fixtures

    jpg = fixture_bytes("imaginary.jpg")
    smart = fixture_bytes("smart-crop.jpg")

    for name, op, kw, expect_wh in MATRIX:
        arr = _run_case(jpg, op, kw)
        assert (arr.shape[1], arr.shape[0]) == expect_wh, (name, arr.shape)
        Image.fromarray(arr).save(os.path.join(out_dir, f"{name}.png"))
        print(f"golden {name}: {arr.shape[1]}x{arr.shape[0]}")

    for name, ops, expect_wh, n_samples in PIPELINES:
        assert _pipeline_sample_count(ops) == n_samples, (name, "plan shape")
        arr = _run_pipeline_case(jpg, ops)
        assert (arr.shape[1], arr.shape[0]) == expect_wh, (name, arr.shape)
        Image.fromarray(arr).save(os.path.join(out_dir, f"{name}.png"))
        print(f"golden {name}: {arr.shape[1]}x{arr.shape[0]} samples={n_samples}")

    name, op, kw, expect_wh = SMARTCROP
    arr = _run_case(smart, op, kw)
    assert (arr.shape[1], arr.shape[0]) == expect_wh, (name, arr.shape)
    Image.fromarray(arr).save(os.path.join(out_dir, f"{name}.png"))
    window = _smartcrop_window(smart, kw)
    with open(os.path.join(out_dir, "smartcrop_window.json"), "w") as f:
        json.dump(window, f, indent=1, sort_keys=True)
    print(f"golden {name}: window={window}")


if __name__ == "__main__":
    generate_all()
    print("goldens written to", GOLDEN_DIR)
