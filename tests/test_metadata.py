"""stripmeta semantics (ref: options.go:139 StripMetadata, default false):
EXIF and ICC survive processing unless stripmeta=true, with Orientation
normalized to 1 once the chain has applied the rotation — libvips
autorotate behavior, now matched by the byte-splice carry in pipeline."""

from io import BytesIO

import numpy as np
from PIL import Image

from imaginary_tpu import codecs, pipeline
from imaginary_tpu.options import ImageOptions
from imaginary_tpu.params import build_params_from_query

# a tiny but structurally valid ICC profile: PIL accepts any bytes tagged
# icc_profile; real readers only need the segment to round-trip intact
FAKE_ICC = b"\x00\x00\x02\x00" + b"ADBE" + b"\x00" * 120


def _jpeg_with_metadata(orientation=6, w=320, h=240) -> bytes:
    rng = np.random.default_rng(5)
    img = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
    exif = Image.Exif()
    exif[274] = orientation  # Orientation
    exif[271] = "imaginary-tpu-test"  # Make
    out = BytesIO()
    Image.fromarray(img).save(
        out, "JPEG", quality=85, subsampling=2,
        exif=exif.tobytes(), icc_profile=FAKE_ICC,
    )
    return out.getvalue()


def _read_meta(body: bytes):
    im = Image.open(BytesIO(body))
    exif = im.getexif()
    return dict(exif), im.info.get("icc_profile")


class TestSegmentHelpers:
    def test_extract_finds_exif_and_icc(self):
        segs = codecs.jpeg_metadata_segments(_jpeg_with_metadata())
        kinds = {s[4:10] for s in segs}
        assert any(k == b"Exif\x00\x00" for k in kinds)
        assert any(s[4:16] == b"ICC_PROFILE\x00" for s in segs)

    def test_no_metadata_yields_empty(self):
        out = BytesIO()
        Image.fromarray(np.zeros((32, 32, 3), np.uint8)).save(out, "JPEG")
        assert codecs.jpeg_metadata_segments(out.getvalue()) == []

    def test_reset_orientation(self):
        segs = codecs.jpeg_metadata_segments(_jpeg_with_metadata(orientation=6))
        exif_seg = next(s for s in segs if s[4:10] == b"Exif\x00\x00")
        patched = codecs.reset_exif_orientation(exif_seg)
        assert patched != exif_seg
        # re-wrap into a minimal JPEG so PIL can parse the patched segment
        out = BytesIO()
        Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(out, "JPEG")
        jpg = codecs.insert_jpeg_segments(out.getvalue(), [patched])
        exif, _ = _read_meta(jpg)
        assert exif[274] == 1
        assert exif[271] == "imaginary-tpu-test"  # other tags untouched


class TestCarryThrough:
    def test_default_preserves_exif_and_icc_with_orientation_reset(self):
        buf = _jpeg_with_metadata(orientation=6)
        out = pipeline.process_operation("resize", buf, ImageOptions(width=100))
        exif, icc = _read_meta(out.body)
        assert exif.get(271) == "imaginary-tpu-test"
        assert exif.get(274) == 1  # rotation was applied, tag normalized
        assert icc == FAKE_ICC
        # the pixels really were rotated: 320x240 oriented -> 240x320 source
        im = Image.open(BytesIO(out.body))
        assert im.size == (100, 133)

    def test_stripmeta_true_strips(self):
        buf = _jpeg_with_metadata()
        o = build_params_from_query({"width": "100", "stripmeta": "true"})
        out = pipeline.process_operation("resize", buf, o)
        exif, icc = _read_meta(out.body)
        assert 271 not in exif
        assert icc is None

    def test_norotation_keeps_original_orientation_tag(self):
        buf = _jpeg_with_metadata(orientation=6)
        o = build_params_from_query({"width": "100", "norotation": "true"})
        out = pipeline.process_operation("resize", buf, o)
        exif, _ = _read_meta(out.body)
        assert exif.get(274) == 6  # pixels unrotated, tag kept faithful

    def test_rgb_path_also_carries(self):
        # PNG output never carries JPEG segments; JPEG output via the RGB
        # transport (force with a 4:4:4 source) still does
        rng = np.random.default_rng(6)
        img = rng.integers(0, 256, (120, 160, 3), dtype=np.uint8)
        exif = Image.Exif()
        exif[271] = "imaginary-tpu-test"
        out = BytesIO()
        Image.fromarray(img).save(out, "JPEG", quality=90, subsampling=0,
                                  exif=exif.tobytes())
        buf = out.getvalue()
        got = pipeline.process_operation("resize", buf, ImageOptions(width=80))
        ex, _ = _read_meta(got.body)
        assert ex.get(271) == "imaginary-tpu-test"

    def test_pipeline_route_carries(self):
        import json

        buf = _jpeg_with_metadata(orientation=1)
        o = build_params_from_query({"operations": json.dumps(
            [{"operation": "resize", "params": {"width": 90}}]
        )})
        out = pipeline.process_pipeline(buf, o)
        exif, icc = _read_meta(out.body)
        assert exif.get(271) == "imaginary-tpu-test"
        assert icc == FAKE_ICC

    def test_pipeline_top_level_stripmeta_wins(self):
        """?stripmeta=true on /pipeline must strip even though per-op
        options default strip_metadata to false (privacy: explicit strip
        requests can never leak EXIF)."""
        import json

        buf = _jpeg_with_metadata()
        o = build_params_from_query({
            "stripmeta": "true",
            "operations": json.dumps(
                [{"operation": "resize", "params": {"width": 90}}]
            ),
        })
        out = pipeline.process_pipeline(buf, o)
        exif, icc = _read_meta(out.body)
        assert 271 not in exif
        assert icc is None

    def test_pipeline_mid_chain_stripmeta_strips(self):
        """stripmeta on ANY pipeline op strips: the reference re-encodes per
        op, so a mid-chain StripMetadata permanently removes metadata even
        when later ops don't set it."""
        import json

        buf = _jpeg_with_metadata()
        o = build_params_from_query({"operations": json.dumps([
            {"operation": "resize", "params": {"width": 100, "stripmeta": "true"}},
            {"operation": "flip", "params": {}},
        ])})
        out = pipeline.process_pipeline(buf, o)
        exif, icc = _read_meta(out.body)
        assert 271 not in exif
        assert icc is None

    def test_fill_bytes_before_marker_still_found(self):
        """ISO 10918-1 B.1.1.2 allows 0xFF fill bytes before any marker;
        the segment scanner must skip them, not abort the scan."""
        buf = _jpeg_with_metadata()
        # inject two fill bytes right after SOI
        padded = buf[:2] + b"\xff\xff" + buf[2:]
        segs = codecs.jpeg_metadata_segments(padded)
        assert any(s[4:10] == b"Exif\x00\x00" for s in segs)

    def test_exif_pixel_dimensions_resync_to_output(self):
        """PixelX/YDimension in the carried EXIF must describe the OUTPUT
        geometry (libvips re-syncs them on save)."""
        rng = np.random.default_rng(9)
        img = rng.integers(0, 256, (240, 320, 3), dtype=np.uint8)
        exif = Image.Exif()
        exif[271] = "imaginary-tpu-test"
        # write ExifIFD dimension tags describing the source
        ifd = exif.get_ifd(0x8769)
        ifd[0xA002] = 320
        ifd[0xA003] = 240
        out = BytesIO()
        Image.fromarray(img).save(out, "JPEG", quality=85, subsampling=2,
                                  exif=exif.tobytes())
        got = pipeline.process_operation(
            "resize", out.getvalue(), ImageOptions(width=100)
        )
        im = Image.open(BytesIO(got.body))
        sub = im.getexif().get_ifd(0x8769)
        assert im.size == (100, 75)
        assert sub.get(0xA002) == 100
        assert sub.get(0xA003) == 75

    def test_pipeline_norotation_first_op_keeps_orientation_tag(self):
        """When the FIRST op sets norotation, the chain never rotates the
        pixels (orientation is consumed once), so the carried Orientation
        tag must stay faithful — even if later ops don't set norotation."""
        import json

        buf = _jpeg_with_metadata(orientation=6)
        o = build_params_from_query({"operations": json.dumps([
            {"operation": "resize", "params": {"width": 100, "norotation": "true"}},
            {"operation": "flip", "params": {}},
        ])})
        out = pipeline.process_pipeline(buf, o)
        exif, _ = _read_meta(out.body)
        assert exif.get(274) == 6
