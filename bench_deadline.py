#!/usr/bin/env python
"""Deadline bookkeeping overhead benchmark: the row ISSUE-4's tentpole is
graded on.

Same harness as bench_obs.py (cache-off zipf hot-URL row — every request
pays fetch -> decode -> process -> encode, so per-request deadline cost
cannot hide behind cache hits), ABBA-interleaved to cancel host drift.
Two arms:

  * deadlines OFF (--request-timeout unset: the parity default — zero
    Deadline objects minted, every call site takes its None fast path)
  * deadlines ON  (--request-timeout 60: every request mints a Deadline
    and pays the note/check bookkeeping at admission, fetch, queue,
    device wait, pool entry, and encode — but never expires)

Prints one JSON line on stdout; human detail on stderr. Exits nonzero
when the ON arm lost more than BENCH_DEADLINE_MAX_OVERHEAD_PCT (default
10 — a gross-regression gate tolerant of short-run noise; the acceptance
criterion is "no measurable overhead" on a full-length run) or when the
ON arm produced any spurious 503/504 under its generous budget.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys

from bench_obs import _arm
from bench_util import ensure_native_built, make_1080p_jpeg, pctl


def main() -> int:
    from bench_cache import N_URLS as CACHE_N_URLS
    from imaginary_tpu.web.config import ServerOptions

    ensure_native_built()
    duration = float(os.environ.get("BENCH_DURATION", "8"))
    concurrency = int(os.environ.get("BENCH_CONCURRENCY", "16"))
    max_overhead = float(os.environ.get("BENCH_DEADLINE_MAX_OVERHEAD_PCT", "10"))

    base_jpeg = make_1080p_jpeg()
    variants = [base_jpeg + b"\x00" * (i + 1) for i in range(CACHE_N_URLS)]

    print(f"[deadline-bench] cache-off zipf row, deadlines on vs off: "
          f"{concurrency} clients x {duration}s per arm, ABBA-interleaved",
          file=sys.stderr)
    slice_s = max(duration / 2.0, 1.0)
    totals = {True: [0.0, [], 0], False: [0.0, [], 0]}
    for arm_on in (False, True, True, False):
        rps, lats, errs = asyncio.run(_arm(
            ServerOptions(enable_url_source=True,
                          request_timeout_s=60.0 if arm_on else 0.0),
            variants, slice_s, concurrency, check_headers=False))
        totals[arm_on][0] += rps
        totals[arm_on][1].extend(lats)
        totals[arm_on][2] += errs
    rps_off, lats_off, err_off = totals[False][0] / 2, totals[False][1], totals[False][2]
    rps_on, lats_on, err_on = totals[True][0] / 2, totals[True][1], totals[True][2]

    overhead_pct = (100.0 * (rps_off - rps_on) / rps_off) if rps_off else 0.0
    row = {
        "metric": "deadline_bookkeeping_overhead",
        "unit": "req/s",
        "value": round(rps_on, 2),
        "value_deadline_off": round(rps_off, 2),
        "overhead_pct": round(overhead_pct, 2),
        "p50_ms": pctl(lats_on, 0.50),
        "p99_ms": pctl(lats_on, 0.99),
        "p50_ms_deadline_off": pctl(lats_off, 0.50),
        "p99_ms_deadline_off": pctl(lats_off, 0.99),
        "errors_on": err_on,
        "errors_off": err_off,
    }
    print(json.dumps(row))

    if err_on > err_off:
        # a generous 60 s budget must never shed or expire a request: any
        # extra error in the ON arm is a correctness bug, not noise
        print(f"[deadline-bench] FAIL: deadline arm added errors "
              f"({err_off} -> {err_on})", file=sys.stderr)
        return 1
    if overhead_pct > max_overhead:
        print(f"[deadline-bench] FAIL: deadline overhead {overhead_pct:.1f}% "
              f"exceeds {max_overhead:.1f}% gate", file=sys.stderr)
        return 1
    print(f"[deadline-bench] deadline overhead {overhead_pct:.1f}% "
          f"({rps_off:.1f} -> {rps_on:.1f} req/s)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
