#!/usr/bin/env python
"""Host-ceiling decomposition: per-stage ms, us vs the cv2 baseline.

BASELINE.md's "~3.6x ceiling on the 1-CPU bench host" claim needs the
decomposition on record, not asserted (VERDICT r4 weak #2): the bench
request is probe -> decode -> transform (device or host spill) -> encode,
and only the TRANSFORM stage can ride the chip — decode/encode are host
C work both for us and for cv2/libvips. This harness times each stage
serially (median of N), prints one JSON line, and derives the ceiling:

    ceiling = T_baseline_total / (T_our_host_fixed + T_transform_min)

where T_our_host_fixed = probe + decode + encode (host-bound no matter
what the accelerator does) and T_transform_min is the transform's floor
(0 for the ideal-chip bound; the measured device or spill time for the
actual configuration).

Usage: python bench_stages.py            # honest backend autodetect
       BENCH_PLATFORM=cpu python bench_stages.py
Artifact: artifacts/host_ceiling_<backend>.json (+ stdout JSON line).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from bench_util import make_1080p_jpeg, pctl, probe_accelerator


def _median_ms(fn, n: int = 60) -> float:
    fn()  # warm
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return pctl(ts, 0.50)


def _byte_touch_audit(buf: bytes) -> dict:
    """Drive the real aiohttp app once cold and once per cache tier, read
    the COPIES ledger around each request, and gate copies-per-hit == 1
    on BOTH tiers (local result LRU and fleet shm)."""
    import asyncio
    import io as _io

    from aiohttp.test_utils import TestClient, TestServer

    from imaginary_tpu.engine.timing import COPIES
    from imaginary_tpu.web.app import create_app
    from imaginary_tpu.web.config import ServerOptions

    async def _request(client):
        COPIES.reset()
        t0 = time.perf_counter_ns()
        res = await client.post("/resize?width=300&height=200", data=buf,
                                headers={"Content-Type": "image/jpeg"})
        body = await res.read()
        ns = time.perf_counter_ns() - t0
        assert res.status == 200, f"byte-touch audit: {res.status}"
        return COPIES.snapshot(), ns, len(body)

    async def _tier(options):
        app = create_app(options, log_stream=_io.StringIO())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            miss = await _request(client)
            hit = await _request(client)
        finally:
            await client.close()
        return miss, hit

    def _row(snap, ns, served):
        total = sum(snap["bytes"].values())
        return {
            "e2e_ns_per_byte": round(ns / max(1, served), 1),
            "copies_per_request": sum(snap["copies"].values()),
            "bytes_copied_per_byte_served": round(total / max(1, served), 2),
            "stages": snap["bytes"],
        }

    def _gate_hit(snap, served, tier):
        # exactly one cache_hit copy of the stored body; the only other
        # booking a hit may make is the single ingress read of the upload
        extra = set(snap["copies"]) - {"cache_hit", "ingress"}
        assert not extra, f"{tier} hit booked extra copy stages: {extra}"
        assert snap["copies"].get("cache_hit") == 1, (
            f"{tier} hit made {snap['copies'].get('cache_hit')} body copies "
            "(copies-per-hit bar is exactly 1)")
        assert snap["bytes"]["cache_hit"] == served, (
            f"{tier} hit touched {snap['bytes']['cache_hit']} body bytes "
            f"for a {served}-byte response")

    async def drive():
        out = {}
        # local result-LRU tier
        (m_snap, m_ns, m_len), (h_snap, h_ns, h_len) = await _tier(
            ServerOptions(cache_result_mb=32.0))
        _gate_hit(h_snap, h_len, "local")
        out["miss"] = _row(m_snap, m_ns, m_len)
        out["local_hit"] = _row(h_snap, h_ns, h_len)
        # fleet shm tier (local LRU off so the second request must come
        # back out of the mmap)
        import tempfile

        from imaginary_tpu.fleet.shmcache import ShmCache

        shm_path = os.path.join(
            tempfile.mkdtemp(prefix="itpu-bench-shm2-"), "shm")
        owner = ShmCache(shm_path, create=True, size_mb=8.0, owner=True)
        os.environ["IMAGINARY_TPU_FLEET_PATH"] = shm_path
        try:
            _, (s_snap, s_ns, s_len) = await _tier(
                ServerOptions(fleet_cache_mb=8.0))
        finally:
            os.environ.pop("IMAGINARY_TPU_FLEET_PATH", None)
            owner.close()
        _gate_hit(s_snap, s_len, "shm")
        out["shm_hit"] = _row(s_snap, s_ns, s_len)
        out["copies_per_hit"] = 1
        return out

    return asyncio.run(drive())


def _spill_dct_row(buf: bytes) -> dict:
    """p50 of the host-spilled baseline-JPEG thumbnail chain, dct
    shrink-on-load vs full-scale reconstruct + resample; gated >= 2x."""
    from imaginary_tpu import pipeline
    from imaginary_tpu.engine import host_exec
    from imaginary_tpu.options import ImageOptions

    o = ImageOptions(width=240, height=135, type="jpeg")
    runner = lambda a, p: host_exec.run(a, p)
    was = pipeline.transport_dct_enabled()
    pipeline.set_transport_dct(True)
    try:
        t_shrink = _median_ms(
            lambda: pipeline.process_operation("thumbnail", buf, o,
                                               runner=runner), n=30)
        orig = pipeline._pick_shrink
        pipeline._pick_shrink = lambda *a, **k: 1
        try:
            t_full = _median_ms(
                lambda: pipeline.process_operation("thumbnail", buf, o,
                                                   runner=runner), n=15)
        finally:
            pipeline._pick_shrink = orig
    finally:
        pipeline.set_transport_dct(was)
    ratio = t_full / t_shrink if t_shrink else 0.0
    assert ratio >= 2.0, (
        f"spill dct shrink-on-load p50 {t_shrink:.2f} ms vs full-scale "
        f"reconstruct {t_full:.2f} ms: {ratio:.2f}x < the 2x bar")
    src = max(1, len(buf))
    return {
        "thumbnail_full_reconstruct_ms": round(t_full, 2),
        "thumbnail_shrink_on_load_ms": round(t_shrink, 2),
        "full_reconstruct_ns_per_src_byte": round(t_full * 1e6 / src, 1),
        "shrink_on_load_ns_per_src_byte": round(t_shrink * 1e6 / src, 1),
        "speedup_x": round(ratio, 2),
    }


def main() -> None:
    platform = os.environ.get("BENCH_PLATFORM", "")
    fallback = False
    if not platform and not probe_accelerator():
        print("[stages] *** ACCELERATOR UNREACHABLE - CPU-JAX FALLBACK ***",
              file=sys.stderr)
        platform = "cpu"
        fallback = True
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    import cv2
    import jax

    from bench_util import ensure_native_built

    ensure_native_built()

    from imaginary_tpu import codecs
    from imaginary_tpu.codecs import EncodeOptions
    from imaginary_tpu.engine import Executor, ExecutorConfig
    from imaginary_tpu.imgtype import ImageType
    from imaginary_tpu.options import ImageOptions
    from imaginary_tpu.ops.plan import choose_decode_shrink, plan_operation

    buf = make_1080p_jpeg()
    opts = ImageOptions(width=300, height=200)

    # ---- our stages (the exact hot-path sequence bench.py runs) ----------
    meta = codecs.probe_fast(buf)
    shrink = choose_decode_shrink("resize", opts, meta.height, meta.width,
                                  meta.orientation, 3)
    d = codecs.decode(buf, shrink)
    plan = plan_operation("resize", opts, d.array.shape[0], d.array.shape[1],
                          d.orientation, d.array.shape[2])

    ours = {
        "probe_ms": _median_ms(lambda: codecs.probe_fast(buf)),
        "decode_ms": _median_ms(lambda: codecs.decode(buf, shrink)),
    }
    # transform, device-primary (batch=1 serial — the decomposition view;
    # throughput amortizes this over micro-batches)
    ex_dev = Executor(ExecutorConfig(window_ms=0.0, max_batch=16, host_spill=False))
    out_arr = ex_dev.process(d.array, plan)
    ours["transform_device_ms"] = _median_ms(lambda: ex_dev.process(d.array, plan))
    ex_dev.shutdown()
    # transform, host-spill interpreter (what serves when the link is slow)
    from imaginary_tpu.engine import host_exec

    ours["transform_host_ms"] = _median_ms(lambda: host_exec.run(d.array, plan))
    ours["encode_ms"] = _median_ms(
        lambda: codecs.encode(out_arr, EncodeOptions(type=ImageType.JPEG)))
    ours["host_fixed_ms"] = round(
        ours["probe_ms"] + ours["decode_ms"] + ours["encode_ms"], 3)

    # host-path /enlarge decomposition (the r5 FAIL row): 1080p full decode
    # -> 2560x1440 separable upsample on the spill interpreter -> encode.
    # The transform is the fix's target; decode/encode bound what any
    # resampler could achieve on this host.
    d_full = codecs.decode(buf, 1)
    eopts = ImageOptions(width=2560, height=1440)
    eplan = plan_operation("enlarge", eopts, d_full.array.shape[0],
                           d_full.array.shape[1], d_full.orientation,
                           d_full.array.shape[2])
    big = host_exec.run(d_full.array, eplan)
    ours["transform_host_enlarge_ms"] = _median_ms(
        lambda: host_exec.run(d_full.array, eplan), n=20)
    ours["encode_enlarge_ms"] = _median_ms(
        lambda: codecs.encode(big, EncodeOptions(type=ImageType.JPEG)), n=20)

    # ---- cache-hit serving byte-touch audit ------------------------------
    # A fleet-cache hit must touch each served byte exactly ONCE (the
    # defensive snapshot out of the mmap); the body handed to the response
    # layer is a zero-copy view of that snapshot. bytes_copied is the
    # tier's own ledger of real copies — pin the invariant here so a
    # future "convenience" bytes() slice reintroducing the second copy
    # fails the bench, not a profiler session.
    import tempfile

    from imaginary_tpu.fleet.shmcache import ShmCache

    shm_path = os.path.join(tempfile.mkdtemp(prefix="itpu-bench-shm-"), "shm")
    shm = ShmCache(shm_path, create=True, size_mb=4.0, owner=True)
    try:
        ckey = b"K" * 32
        cmeta = b"image/jpeg\n"
        cbody = buf[:96 * 1024]  # shm entries are slot-capped at 128 KB
        assert shm.put(ckey, cmeta, cbody), "cache-hit audit: deposit refused"
        before = shm.stats.bytes_copied
        hit = shm.get(ckey)
        assert hit is not None, "cache-hit audit: deposit did not read back"
        hmeta, hbody = hit
        touched = shm.stats.bytes_copied - before
        assert isinstance(hbody, memoryview), \
            "cache-hit audit: body is not a zero-copy view"
        assert bytes(hbody) == cbody and bytes(hmeta) == cmeta
        assert touched == len(cmeta) + len(cbody), (
            f"cache-hit audit: hit touched {touched} bytes for a "
            f"{len(cmeta) + len(cbody)}-byte payload (expected exactly one "
            "snapshot copy)")
        ours["cache_hit_ms"] = _median_ms(lambda: shm.get(ckey), n=40)
        ours["cache_hit_bytes_per_byte"] = 1.0
    finally:
        shm.close()

    # ---- end-to-end byte-touch ledger (engine/timing.COPIES) -------------
    # The per-request journey (ingress -> decode -> transform -> encode ->
    # response) graded in ns per served byte and COPIES per request, plus
    # the cache-hit audit through the REAL handler path on both tiers:
    # a hit must book exactly ONE cache_hit copy (the single read of the
    # stored body) and nothing else beyond the ingress read. Archived to
    # artifacts/host_bytes_<backend>.json; a regression here is a second
    # body materialization someone added for convenience.
    host_bytes = _byte_touch_audit(buf)

    # ---- spill path: DCT shrink-on-load vs full-scale reconstruct --------
    # When a dct-transport plan spills to the host (saturated link, open
    # breaker, --force-host), shrink-on-load folds the coefficients to the
    # k-point basis at decode and IDCTs straight to the shrunk size; the
    # old cost was a full-scale k=8 reconstruction plus a host resample.
    # Gate: >= 2x on the baseline-JPEG thumbnail chain.
    host_bytes["spill_dct"] = _spill_dct_row(buf)

    # ---- cv2 baseline stages (same work split) ---------------------------
    data = np.frombuffer(buf, np.uint8)
    a = cv2.imdecode(data, cv2.IMREAD_COLOR)
    r = cv2.resize(a, (300, 200), interpolation=cv2.INTER_AREA)
    jq = [int(cv2.IMWRITE_JPEG_QUALITY), 80]
    base = {
        "decode_ms": _median_ms(lambda: cv2.imdecode(data, cv2.IMREAD_COLOR)),
        "transform_ms": _median_ms(
            lambda: cv2.resize(a, (300, 200), interpolation=cv2.INTER_AREA)),
        "encode_ms": _median_ms(lambda: cv2.imencode(".jpg", r, jq)),
    }
    base["total_ms"] = round(sum(base.values()), 3)
    # the cv2 equivalent of the enlarge transform (bicubic, the latency
    # bench's baseline op) — NOT in total_ms, which grades the resize row
    base["enlarge_transform_ms"] = _median_ms(
        lambda: cv2.resize(a, (2560, 1440), interpolation=cv2.INTER_CUBIC),
        n=20)

    # ---- ceiling math ----------------------------------------------------
    # On a 1-CPU host, serial rates bound single-process throughput. The
    # ideal-chip ceiling zeroes the transform; the spill ceiling uses the
    # host interpreter's transform (what the cost model actually serves
    # over a saturated link).
    ceil_ideal = base["total_ms"] / ours["host_fixed_ms"] if ours["host_fixed_ms"] else 0.0
    ceil_spill = base["total_ms"] / (ours["host_fixed_ms"] + ours["transform_host_ms"])

    backend = "cpu-fallback" if fallback else jax.default_backend()
    result = {
        "metric": "host_ceiling_decomposition_resize_1080p",
        "backend": backend,
        "ours": ours,
        "cv2_baseline": base,
        "ceiling_ideal_chip_x": round(ceil_ideal, 2),
        "ceiling_host_spill_x": round(ceil_spill, 2),
        "note": ("ceiling_ideal_chip_x = cv2_total / our host-fixed work "
                 "(probe+decode+encode): the single-process per-request "
                 "speedup bound on THIS host even with an infinitely fast "
                 "accelerator; decode/encode parallelism across workers/"
                 "cores is what raises it"),
    }
    os.makedirs("artifacts", exist_ok=True)
    path = os.path.join("artifacts", f"host_ceiling_{backend}.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(f"[stages] wrote {path}", file=sys.stderr)

    bytes_result = {
        "metric": "host_byte_touch_resize_1080p",
        "backend": backend,
        **host_bytes,
        "note": ("copies_per_hit is gated at exactly 1 on both cache "
                 "tiers (the single read of the stored body); spill_dct "
                 "gates the dct shrink-on-load thumbnail chain at >= 2x "
                 "over full-scale reconstruction"),
    }
    bpath = os.path.join("artifacts", f"host_bytes_{backend}.json")
    with open(bpath, "w") as f:
        json.dump(bytes_result, f, indent=1)
    print(f"[stages] wrote {bpath}", file=sys.stderr)
    print(json.dumps(result))
    print(json.dumps(bytes_result))


if __name__ == "__main__":
    main()
