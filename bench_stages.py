#!/usr/bin/env python
"""Host-ceiling decomposition: per-stage ms, us vs the cv2 baseline.

BASELINE.md's "~3.6x ceiling on the 1-CPU bench host" claim needs the
decomposition on record, not asserted (VERDICT r4 weak #2): the bench
request is probe -> decode -> transform (device or host spill) -> encode,
and only the TRANSFORM stage can ride the chip — decode/encode are host
C work both for us and for cv2/libvips. This harness times each stage
serially (median of N), prints one JSON line, and derives the ceiling:

    ceiling = T_baseline_total / (T_our_host_fixed + T_transform_min)

where T_our_host_fixed = probe + decode + encode (host-bound no matter
what the accelerator does) and T_transform_min is the transform's floor
(0 for the ideal-chip bound; the measured device or spill time for the
actual configuration).

Usage: python bench_stages.py            # honest backend autodetect
       BENCH_PLATFORM=cpu python bench_stages.py
Artifact: artifacts/host_ceiling_<backend>.json (+ stdout JSON line).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from bench_util import make_1080p_jpeg, pctl, probe_accelerator


def _median_ms(fn, n: int = 60) -> float:
    fn()  # warm
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return pctl(ts, 0.50)


def main() -> None:
    platform = os.environ.get("BENCH_PLATFORM", "")
    fallback = False
    if not platform and not probe_accelerator():
        print("[stages] *** ACCELERATOR UNREACHABLE - CPU-JAX FALLBACK ***",
              file=sys.stderr)
        platform = "cpu"
        fallback = True
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    import cv2
    import jax

    from bench_util import ensure_native_built

    ensure_native_built()

    from imaginary_tpu import codecs
    from imaginary_tpu.codecs import EncodeOptions
    from imaginary_tpu.engine import Executor, ExecutorConfig
    from imaginary_tpu.imgtype import ImageType
    from imaginary_tpu.options import ImageOptions
    from imaginary_tpu.ops.plan import choose_decode_shrink, plan_operation

    buf = make_1080p_jpeg()
    opts = ImageOptions(width=300, height=200)

    # ---- our stages (the exact hot-path sequence bench.py runs) ----------
    meta = codecs.probe_fast(buf)
    shrink = choose_decode_shrink("resize", opts, meta.height, meta.width,
                                  meta.orientation, 3)
    d = codecs.decode(buf, shrink)
    plan = plan_operation("resize", opts, d.array.shape[0], d.array.shape[1],
                          d.orientation, d.array.shape[2])

    ours = {
        "probe_ms": _median_ms(lambda: codecs.probe_fast(buf)),
        "decode_ms": _median_ms(lambda: codecs.decode(buf, shrink)),
    }
    # transform, device-primary (batch=1 serial — the decomposition view;
    # throughput amortizes this over micro-batches)
    ex_dev = Executor(ExecutorConfig(window_ms=0.0, max_batch=16, host_spill=False))
    out_arr = ex_dev.process(d.array, plan)
    ours["transform_device_ms"] = _median_ms(lambda: ex_dev.process(d.array, plan))
    ex_dev.shutdown()
    # transform, host-spill interpreter (what serves when the link is slow)
    from imaginary_tpu.engine import host_exec

    ours["transform_host_ms"] = _median_ms(lambda: host_exec.run(d.array, plan))
    ours["encode_ms"] = _median_ms(
        lambda: codecs.encode(out_arr, EncodeOptions(type=ImageType.JPEG)))
    ours["host_fixed_ms"] = round(
        ours["probe_ms"] + ours["decode_ms"] + ours["encode_ms"], 3)

    # host-path /enlarge decomposition (the r5 FAIL row): 1080p full decode
    # -> 2560x1440 separable upsample on the spill interpreter -> encode.
    # The transform is the fix's target; decode/encode bound what any
    # resampler could achieve on this host.
    d_full = codecs.decode(buf, 1)
    eopts = ImageOptions(width=2560, height=1440)
    eplan = plan_operation("enlarge", eopts, d_full.array.shape[0],
                           d_full.array.shape[1], d_full.orientation,
                           d_full.array.shape[2])
    big = host_exec.run(d_full.array, eplan)
    ours["transform_host_enlarge_ms"] = _median_ms(
        lambda: host_exec.run(d_full.array, eplan), n=20)
    ours["encode_enlarge_ms"] = _median_ms(
        lambda: codecs.encode(big, EncodeOptions(type=ImageType.JPEG)), n=20)

    # ---- cache-hit serving byte-touch audit ------------------------------
    # A fleet-cache hit must touch each served byte exactly ONCE (the
    # defensive snapshot out of the mmap); the body handed to the response
    # layer is a zero-copy view of that snapshot. bytes_copied is the
    # tier's own ledger of real copies — pin the invariant here so a
    # future "convenience" bytes() slice reintroducing the second copy
    # fails the bench, not a profiler session.
    import tempfile

    from imaginary_tpu.fleet.shmcache import ShmCache

    shm_path = os.path.join(tempfile.mkdtemp(prefix="itpu-bench-shm-"), "shm")
    shm = ShmCache(shm_path, create=True, size_mb=4.0, owner=True)
    try:
        ckey = b"K" * 32
        cmeta = b"image/jpeg\n"
        cbody = buf[:96 * 1024]  # shm entries are slot-capped at 128 KB
        assert shm.put(ckey, cmeta, cbody), "cache-hit audit: deposit refused"
        before = shm.stats.bytes_copied
        hit = shm.get(ckey)
        assert hit is not None, "cache-hit audit: deposit did not read back"
        hmeta, hbody = hit
        touched = shm.stats.bytes_copied - before
        assert isinstance(hbody, memoryview), \
            "cache-hit audit: body is not a zero-copy view"
        assert bytes(hbody) == cbody and bytes(hmeta) == cmeta
        assert touched == len(cmeta) + len(cbody), (
            f"cache-hit audit: hit touched {touched} bytes for a "
            f"{len(cmeta) + len(cbody)}-byte payload (expected exactly one "
            "snapshot copy)")
        ours["cache_hit_ms"] = _median_ms(lambda: shm.get(ckey), n=40)
        ours["cache_hit_bytes_per_byte"] = 1.0
    finally:
        shm.close()

    # ---- cv2 baseline stages (same work split) ---------------------------
    data = np.frombuffer(buf, np.uint8)
    a = cv2.imdecode(data, cv2.IMREAD_COLOR)
    r = cv2.resize(a, (300, 200), interpolation=cv2.INTER_AREA)
    jq = [int(cv2.IMWRITE_JPEG_QUALITY), 80]
    base = {
        "decode_ms": _median_ms(lambda: cv2.imdecode(data, cv2.IMREAD_COLOR)),
        "transform_ms": _median_ms(
            lambda: cv2.resize(a, (300, 200), interpolation=cv2.INTER_AREA)),
        "encode_ms": _median_ms(lambda: cv2.imencode(".jpg", r, jq)),
    }
    base["total_ms"] = round(sum(base.values()), 3)
    # the cv2 equivalent of the enlarge transform (bicubic, the latency
    # bench's baseline op) — NOT in total_ms, which grades the resize row
    base["enlarge_transform_ms"] = _median_ms(
        lambda: cv2.resize(a, (2560, 1440), interpolation=cv2.INTER_CUBIC),
        n=20)

    # ---- ceiling math ----------------------------------------------------
    # On a 1-CPU host, serial rates bound single-process throughput. The
    # ideal-chip ceiling zeroes the transform; the spill ceiling uses the
    # host interpreter's transform (what the cost model actually serves
    # over a saturated link).
    ceil_ideal = base["total_ms"] / ours["host_fixed_ms"] if ours["host_fixed_ms"] else 0.0
    ceil_spill = base["total_ms"] / (ours["host_fixed_ms"] + ours["transform_host_ms"])

    backend = "cpu-fallback" if fallback else jax.default_backend()
    result = {
        "metric": "host_ceiling_decomposition_resize_1080p",
        "backend": backend,
        "ours": ours,
        "cv2_baseline": base,
        "ceiling_ideal_chip_x": round(ceil_ideal, 2),
        "ceiling_host_spill_x": round(ceil_spill, 2),
        "note": ("ceiling_ideal_chip_x = cv2_total / our host-fixed work "
                 "(probe+decode+encode): the single-process per-request "
                 "speedup bound on THIS host even with an infinitely fast "
                 "accelerator; decode/encode parallelism across workers/"
                 "cores is what raises it"),
    }
    os.makedirs("artifacts", exist_ok=True)
    path = os.path.join("artifacts", f"host_ceiling_{backend}.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(f"[stages] wrote {path}", file=sys.stderr)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
