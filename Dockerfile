# imaginary-tpu container image (role of the reference's multi-stage
# Dockerfile: build native code, run tests, ship a slim runtime with the
# loader libraries + an allocator tuned for a long-lived image service).
#
# Build:  docker build -t imaginary-tpu .
# Run:    docker run -p 9000:9000 imaginary-tpu --enable-url-source
#
# TPU note: on a TPU VM run with the libtpu device mounted
# (`--device /dev/accel0 --privileged` or the tpu-device-plugin on GKE) and
# a jax[tpu]-capable base; JAX_PLATFORMS=cpu makes the same image serve on
# CPU-only hosts.

# ---- build stage: compile the native codec extension -----------------------
FROM python:3.12-slim-bookworm AS build

RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ make libjpeg62-turbo-dev libpng-dev libwebp-dev libtiff-dev \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /src
COPY imaginary_tpu/ imaginary_tpu/
RUN python -m imaginary_tpu.native.build

# ---- test stage: unit suite on an 8-device CPU mesh (race-detector role) ---
FROM build AS test

RUN pip install --no-cache-dir jax flax optax einops numpy pillow pytest \
    opencv-python-headless aiohttp
COPY tests/ tests/
COPY conftest.py* ./
RUN JAX_PLATFORMS=cpu python -m pytest tests/ -x -q && touch /tests-passed

# ---- runtime ---------------------------------------------------------------
FROM python:3.12-slim-bookworm

# Loader libraries for SVG/PDF/HEIF/AVIF (ctypes bindings in
# codecs/vector_backend.py), codec shared objects for the native extension,
# and real truetype fonts for pango-style watermark specs (ops/text.py).
RUN apt-get update && apt-get install -y --no-install-recommends \
    libjpeg62-turbo libpng16-16 libwebp7 libtiff6 \
    librsvg2-2 libcairo2 libpoppler-glib8 libheif1 \
    libnghttp2-14 \
    fonts-dejavu-core curl \
    && rm -rf /var/lib/apt/lists/*

RUN pip install --no-cache-dir jax flax optax einops numpy pillow \
    opencv-python-headless aiohttp
# For TPU VMs swap the line above for:
#   pip install 'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

WORKDIR /app
COPY imaginary_tpu/ imaginary_tpu/
COPY --from=build /src/imaginary_tpu/native/_imaginary_codecs*.so imaginary_tpu/native/
# depending on the test stage forces `docker build` to actually run it
# (BuildKit prunes stages the final image doesn't reference)
COPY --from=test /tests-passed /tmp/tests-passed

# Long-lived glibc processes fragment under per-request allocation churn;
# capping arenas is the stock mitigation (the reference LD_PRELOADs jemalloc
# for the same reason, and documents MALLOC_ARENA_MAX=2 — README.md:235).
# HOME=/tmp: the XLA persistent compile cache lives under ~/.cache and the
# runtime user `nobody` has no real home directory.
ENV MALLOC_ARENA_MAX=2 \
    PYTHONUNBUFFERED=1 \
    HOME=/tmp \
    PORT=9000

EXPOSE 9000
USER nobody

HEALTHCHECK --interval=30s --timeout=5s --start-period=120s \
    CMD curl -sf http://127.0.0.1:9000/health || exit 1

ENTRYPOINT ["python", "-m", "imaginary_tpu"]
CMD ["--port", "9000"]
