#!/usr/bin/env python
"""Memory-pressure firehose (`make bench-memory`, wired into `make gate`).

One A-B row proving the ISSUE 7 acceptance shape: a bomb + oversize-
enlarge firehose against an in-process server, governor ON vs OFF.

  * ON arm (first, so the OFF arm's RSS growth cannot contaminate its
    measurement): --max-allowed-resolution 18 and the pressure governor
    armed with its RSS ceiling AT the current baseline — the honest
    worst case, "the operator's ceiling is where we already are", so the
    ladder is critical from the first sample. Invariants: availability
    (well-formed responses) >= 95%, statuses ONLY in {200, 413, 503,
    504} with real 200s among them, ZERO raw 5xx / exceptions / process
    deaths, and peak RSS under baseline + BENCH_RSS_CEILING_MB.
  * OFF arm: every guard off (--max-allowed-resolution 0, no governor).
    The same firehose decodes the bombs' declared frames and
    materializes the oversize outputs; peak RSS must EXCEED the ceiling
    the governed arm held — that gap is the subsystem's reason to exist.

Bombs are structurally valid PNG headers declaring ~100-megapixel frames
over one token row of data (the decompression-bomb shape); enlarges ask
a 1080p source for a 33 MP output. Peak RSS is sampled from
/proc/self/status every 25 ms by a background task.

Prints one JSON line on stdout; human detail on stderr; nonzero exit on
any violated invariant.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import os
import struct
import sys
import time
import zlib


def _png_bomb(w: int = 10000, h: int = 10000) -> bytes:
    def chunk(tag: bytes, payload: bytes) -> bytes:
        body = tag + payload
        return (struct.pack(">I", len(payload)) + body
                + struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF))

    return (b"\x89PNG\r\n\x1a\n"
            + chunk(b"IHDR", struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0))
            + chunk(b"IDAT", zlib.compress(b"\x00" * (w * 3 + 1)))
            + chunk(b"IEND", b""))


async def _rss_sampler(peak: list, stop: asyncio.Event) -> None:
    from imaginary_tpu.web.health import _rss_mb

    while not stop.is_set():
        peak[0] = max(peak[0], _rss_mb())
        await asyncio.sleep(0.025)


async def _arm(options, duration: float, concurrency: int,
               origin_base: str, base: str) -> dict:
    import aiohttp

    counts: dict = {}
    peak = [0.0]
    stop = asyncio.Event()
    sampler = asyncio.create_task(_rss_sampler(peak, stop))
    # the firehose mix: 1 bomb : 1 oversize enlarge : 2 modest resizes
    urls = itertools.cycle([
        f"{base}/resize?width=100&height=100&url={origin_base}/bomb.png",
        f"{base}/enlarge?width=7680&height=4320&url={origin_base}/img.jpg",
        f"{base}/resize?width=300&height=200&url={origin_base}/img.jpg",
        f"{base}/resize?width=320&height=240&url={origin_base}/img.jpg",
    ])
    deadline = time.monotonic() + duration
    conn = aiohttp.TCPConnector(limit=0)
    try:
        async with aiohttp.ClientSession(connector=conn) as session:

            async def worker():
                while time.monotonic() < deadline:
                    try:
                        async with session.get(next(urls)) as res:
                            await res.read()
                            counts[res.status] = counts.get(res.status, 0) + 1
                    except Exception:
                        counts["exc"] = counts.get("exc", 0) + 1

            await asyncio.gather(*[worker() for _ in range(concurrency)])
    finally:
        stop.set()
        await sampler
    return {"counts": counts, "peak_rss_mb": peak[0]}


async def _run(duration: float, concurrency: int, ceiling_add_mb: float) -> dict:
    from aiohttp import web

    from bench_cache import _start_server
    from bench_util import free_port, make_1080p_jpeg
    from imaginary_tpu.web.config import ServerOptions
    from imaginary_tpu.web.health import _rss_mb

    # origin serving the bomb and the enlarge source
    bomb = _png_bomb()
    jpeg = make_1080p_jpeg()

    async def origin_handler(request):
        if request.path.endswith("bomb.png"):
            return web.Response(body=bomb, content_type="image/png")
        return web.Response(body=jpeg, content_type="image/jpeg")

    oapp = web.Application()
    oapp.router.add_get("/{tail:.*}", origin_handler)
    orunner = web.AppRunner(oapp, access_log=None)
    await orunner.setup()
    oport = free_port()
    await web.TCPSite(orunner, "127.0.0.1", oport).start()
    origin_base = f"http://127.0.0.1:{oport}"

    try:
        # decode one small source + touch the executor once so the
        # baseline includes runtime init (jax, codec backends), not the
        # firehose's fault
        from imaginary_tpu import codecs

        codecs.decode(jpeg)
        baseline = _rss_mb()
        ceiling = baseline + ceiling_add_mb

        # --- ON arm first: its peak must not be polluted by OFF's growth
        on_runner, on_app, on_base = await _start_server(ServerOptions(
            enable_url_source=True, request_timeout_s=10.0,
            max_allowed_pixels=18.0,
            pressure_rss_mb=max(baseline, 1.0)))
        try:
            on = await _arm(None, duration, concurrency, origin_base, on_base)
            on["pressure"] = on_app["service"].pressure.snapshot()
        finally:
            await on_runner.cleanup()

        # --- OFF arm: every guard off, same firehose
        off_runner, off_app, off_base = await _start_server(ServerOptions(
            enable_url_source=True, request_timeout_s=30.0,
            max_allowed_pixels=0.0))
        try:
            off = await _arm(None, duration, concurrency, origin_base,
                             off_base)
        finally:
            await off_runner.cleanup()
    finally:
        await orunner.cleanup()
    return {"baseline_rss_mb": baseline, "ceiling_mb": ceiling,
            "on": on, "off": off}


def main() -> int:
    from bench_util import ensure_native_built

    ensure_native_built()
    duration = float(os.environ.get("BENCH_DURATION", "6")) / 2.0
    concurrency = int(os.environ.get("BENCH_CONCURRENCY", "8"))
    ceiling_add = float(os.environ.get("BENCH_RSS_CEILING_MB", "192"))

    print(f"[memory] firehose: {concurrency} clients x {duration:.1f}s/arm, "
          f"ceiling = baseline + {ceiling_add:.0f} MB", file=sys.stderr)
    got = asyncio.run(_run(duration, concurrency, ceiling_add))

    on, off = got["on"], got["off"]
    ceiling = got["ceiling_mb"]
    on_counts = on["counts"]
    on_total = sum(on_counts.values())
    allowed = sum(on_counts.get(s, 0) for s in (200, 413, 503, 504))
    row = {
        "metric": "memory_firehose",
        "baseline_rss_mb": round(got["baseline_rss_mb"], 1),
        "rss_ceiling_mb": round(ceiling, 1),
        "peak_rss_mb_governor_on": round(on["peak_rss_mb"], 1),
        "peak_rss_mb_governor_off": round(off["peak_rss_mb"], 1),
        "requests_on": on_total,
        "ok_on": on_counts.get(200, 0),
        "availability_on": round(allowed / on_total, 4) if on_total else 0.0,
        "pressure_level_end": on["pressure"]["level"],
        "pixel_clamps": on["pressure"]["pixel_clamps"],
        "counts_on": {str(k): v for k, v in sorted(on_counts.items(), key=str)},
        "counts_off": {str(k): v
                       for k, v in sorted(off["counts"].items(), key=str)},
    }
    # archive the governed/ungoverned RSS ceilings; when a previous run's
    # artifact exists, the delta rides along so an RSS regression shows up
    # as a diff in review, not as an incident. The governed peak is
    # additionally gated against the previous run (+16 MB sampling slack).
    os.makedirs("artifacts", exist_ok=True)
    apath = os.path.join("artifacts", "memory_firehose.json")
    prev = None
    if os.path.exists(apath):
        try:
            with open(apath) as f:
                prev = json.load(f)
        except (OSError, ValueError):
            prev = None
    if prev is not None:
        row["prev_peak_rss_mb_governor_on"] = prev.get(
            "peak_rss_mb_governor_on")
        row["prev_peak_rss_mb_governor_off"] = prev.get(
            "peak_rss_mb_governor_off")
        if isinstance(row["prev_peak_rss_mb_governor_on"], (int, float)):
            row["delta_peak_rss_mb_governor_on"] = round(
                row["peak_rss_mb_governor_on"]
                - row["prev_peak_rss_mb_governor_on"], 1)
    with open(apath, "w") as f:
        json.dump(row, f, indent=1)
    print(f"[memory] wrote {apath}", file=sys.stderr)
    print(json.dumps(row))

    fails = []
    prev_on = row.get("prev_peak_rss_mb_governor_on")
    if isinstance(prev_on, (int, float)) and \
            row["peak_rss_mb_governor_on"] > prev_on + 16.0:
        fails.append(
            f"governed peak RSS {row['peak_rss_mb_governor_on']:.0f} MB "
            f"regressed past the previous run's {prev_on:.0f} MB")
    if on_total == 0:
        fails.append("governed arm produced zero requests")
    if on_total and allowed / on_total < 0.95:
        fails.append(f"availability {allowed}/{on_total} below 95% "
                     "(well-formed 200/413/503/504)")
    surprises = {k: v for k, v in on_counts.items()
                 if k not in (200, 413, 503, 504)}
    if surprises:
        fails.append(f"governed arm statuses outside 200/413/503/504: "
                     f"{surprises}")
    if on_counts.get(200, 0) == 0:
        fails.append("governed arm served zero 200s (clamp over-shed)")
    if on["peak_rss_mb"] > ceiling:
        fails.append(f"governed peak RSS {on['peak_rss_mb']:.0f} MB broke "
                     f"the {ceiling:.0f} MB ceiling")
    if off["peak_rss_mb"] <= ceiling:
        fails.append(f"ungoverned peak RSS {off['peak_rss_mb']:.0f} MB never "
                     f"exceeded the {ceiling:.0f} MB ceiling — the A-B "
                     "proves nothing on this host/workload")
    if fails:
        for f in fails:
            print(f"[memory] FAIL: {f}", file=sys.stderr)
        return 1
    print(f"[memory] PASS: governed peak {on['peak_rss_mb']:.0f} MB <= "
          f"ceiling {ceiling:.0f} MB < ungoverned peak "
          f"{off['peak_rss_mb']:.0f} MB; availability "
          f"{row['availability_on']:.1%}, {row['ok_on']} 200s, "
          f"{row['pixel_clamps']} clamps, zero deaths", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
