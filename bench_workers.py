#!/usr/bin/env python
"""Multi-process serving throughput: --workers N over HTTP.

The in-process `bench.py` measures the executor path; this harness
measures what --workers actually buys END-TO-END: it boots a real fleet
on SO_REUSEPORT, drives closed-loop HTTP clients at /resize (1080p JPEG,
the headline workload), and reports req/s per worker count.

On a 1-CPU host N>1 is expected to hold ~parity (the cores are the
binding resource — the point of the artifact is the mechanism's cost,
not a speedup this host cannot produce); on an M-core host the VERDICT
acceptance is >=1.7x at N=2. One JSON line per worker count.

Usage: python bench_workers.py            # N in {1, 2}
       BENCH_WORKERS="1 2 4" BENCH_DURATION=15 python bench_workers.py
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

from bench_util import free_port, make_1080p_jpeg, pctl, run_workers


def _wait_healthy(port: int, deadline_s: float = 120.0) -> None:
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=2)
            return
        except Exception:
            time.sleep(0.5)
    raise RuntimeError("fleet never became healthy")


def bench_n(n: int, body: bytes, duration: float, n_threads: int) -> dict:
    port = free_port()
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", env.get("BENCH_PLATFORM", "cpu"))
    env.pop("IMAGINARY_TPU_WORKER", None)
    sup = subprocess.Popen(
        [sys.executable, "-m", "imaginary_tpu.cli", "--workers", str(n),
         "--port", str(port)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        _wait_healthy(port)
        url = f"http://127.0.0.1:{port}/resize?width=300&height=200"

        def one(k, i):
            req = urllib.request.Request(
                url, data=body, headers={"Content-Type": "image/jpeg",
                                         "Connection": "close"})
            with urllib.request.urlopen(req, timeout=120) as r:
                r.read()
                assert r.status == 200

        # warm every worker's compile ladder (kernel round-robins
        # connections; a few times the thread count reaches them all)
        run_workers(one, max(6.0, duration / 2), n_threads)
        rate, lats = run_workers(one, duration, n_threads)
        return {
            "metric": "workers_http_resize_1080p",
            "workers": n,
            "value": round(rate, 2),
            "unit": "req/sec",
            "p50_ms": pctl(lats, 0.50),
            "p99_ms": pctl(lats, 0.99),
            "cpus": os.cpu_count() or 1,
        }
    finally:
        sup.send_signal(signal.SIGTERM)
        try:
            sup.wait(timeout=30)
        except subprocess.TimeoutExpired:
            sup.kill()
            sup.wait()


def main() -> None:
    duration = float(os.environ.get("BENCH_DURATION", "12"))
    n_threads = int(os.environ.get("BENCH_THREADS", "16"))
    counts = [int(x) for x in os.environ.get("BENCH_WORKERS", "1 2").split()]
    body = make_1080p_jpeg()
    results = []
    for n in counts:
        res = bench_n(n, body, duration, n_threads)
        results.append(res)
        print(f"[workers] N={n}: {res['value']} req/s "
              f"p50={res['p50_ms']}ms p99={res['p99_ms']}ms", file=sys.stderr)
        print(json.dumps(res), flush=True)
    if len(results) >= 2 and results[0]["value"] > 0:
        ratio = results[1]["value"] / results[0]["value"]
        print(f"[workers] N={counts[1]}/N={counts[0]} ratio: {ratio:.2f}x "
              f"on a {os.cpu_count()}-core host", file=sys.stderr)


if __name__ == "__main__":
    main()
