#!/usr/bin/env python
"""Multi-process serving throughput: --workers N over HTTP.

The in-process `bench.py` measures the executor path; this harness
measures what --workers actually buys END-TO-END: it boots a real fleet
on SO_REUSEPORT, drives closed-loop HTTP clients at /resize (1080p JPEG,
the headline workload), and reports req/s per worker count.

On a 1-CPU host N>1 is expected to hold ~parity (the cores are the
binding resource — the point of the artifact is the mechanism's cost,
not a speedup this host cannot produce); on an M-core host the VERDICT
acceptance is >=1.7x at N=2. One JSON line per worker count.

A second row (ISSUE 11) A/Bs the fleet shared cache: N workers on a
zipf hot-URL workload with N INDEPENDENT result caches vs the same
caches tiered over the crash-safe shm cache — cross-worker hits mean a
result any worker computed serves the whole fleet, so the shm arm must
beat (or at minimum match) the independent arm, with the cross-worker
hit ratio reported. BENCH_SHM_AB=0 skips it.

Usage: python bench_workers.py            # N in {1, 2}
       BENCH_WORKERS="1 2 4" BENCH_DURATION=15 python bench_workers.py
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

from bench_util import free_port, make_1080p_jpeg, pctl, run_workers


def _wait_healthy(port: int, deadline_s: float = 120.0) -> None:
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=2)
            return
        except Exception:
            time.sleep(0.5)
    raise RuntimeError("fleet never became healthy")


def bench_n(n: int, body: bytes, duration: float, n_threads: int) -> dict:
    port = free_port()
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", env.get("BENCH_PLATFORM", "cpu"))
    env.pop("IMAGINARY_TPU_WORKER", None)
    sup = subprocess.Popen(
        [sys.executable, "-m", "imaginary_tpu.cli", "--workers", str(n),
         "--port", str(port)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        _wait_healthy(port)
        url = f"http://127.0.0.1:{port}/resize?width=300&height=200"

        def one(k, i):
            req = urllib.request.Request(
                url, data=body, headers={"Content-Type": "image/jpeg",
                                         "Connection": "close"})
            with urllib.request.urlopen(req, timeout=120) as r:
                r.read()
                assert r.status == 200

        # warm every worker's compile ladder (kernel round-robins
        # connections; a few times the thread count reaches them all)
        run_workers(one, max(6.0, duration / 2), n_threads)
        rate, lats = run_workers(one, duration, n_threads)
        return {
            "metric": "workers_http_resize_1080p",
            "workers": n,
            "value": round(rate, 2),
            "unit": "req/sec",
            "p50_ms": pctl(lats, 0.50),
            "p99_ms": pctl(lats, 0.99),
            "cpus": os.cpu_count() or 1,
        }
    finally:
        sup.send_signal(signal.SIGTERM)
        try:
            sup.wait(timeout=30)
        except subprocess.TimeoutExpired:
            sup.kill()
            sup.wait()


# --- fleet shared-cache A/B (ISSUE 11) ---------------------------------------

# zipf-ish hot-URL workload: enough distinct URLs (and a flat-enough
# tail) that miss traffic dominates the measured window — per-worker
# INDEPENDENT caches pay every URL's compute once per worker, while the
# shm tier pays it once per FLEET. The arms measure from COLD result
# caches (the warmup touches one dedicated URL, enough to absorb
# compile/boot costs): the difference between the arms IS the miss
# traffic, so a pre-warmed measurement window would show nothing. Run
# ABBA (off-on-on-off) so slow host drift cancels out of the ratio.
SHM_AB_URLS = 192
SHM_AB_ZIPF = 0.7


def _zipf_seq(n: int, n_urls: int, s: float) -> list:
    import numpy as np

    rng = np.random.default_rng(11)
    weights = 1.0 / np.arange(1, n_urls + 1) ** s
    weights /= weights.sum()
    return list(rng.choice(n_urls, size=n, p=weights))


def _start_origin(variants: list):
    """Stdlib threading origin serving /img/{i} (the fleet workers are
    subprocesses, so the origin must be a real listener, but it needs no
    asyncio — bench_workers is a sync harness)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            try:
                i = int(self.path.rsplit("/", 1)[-1]) % len(variants)
            except ValueError:
                self.send_error(404)
                return
            body = variants[i]
            self.send_response(200)
            self.send_header("Content-Type", "image/jpeg")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_port}"


def _sum_fleet_counters(port: int, samples: int = 30) -> dict:
    """Sum the per-worker fleet blocks (sample /health until both pids
    seen; counters only grow, keep each pid's latest)."""
    per_pid: dict = {}
    for _ in range(samples):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=2) as r:
                h = json.loads(r.read())
            if "fleet" in h:
                per_pid[h["pid"]] = h["fleet"]
        except Exception:
            time.sleep(0.1)
    out = {"workers_seen": len(per_pid)}
    for k in ("hits", "misses", "publishes", "corrupt", "corrupt_served"):
        out[k] = sum(v.get(k, 0) for v in per_pid.values())
    for k in ("forwards", "serve_forwarded", "waiter_hits",
              "local_fallbacks"):
        out["coh_" + k] = sum(v.get("coherence", {}).get(k, 0)
                              for v in per_pid.values())
    return out


def _shm_arm(n: int, origin_base: str, seq: list, duration: float,
             n_threads: int, shm_on: bool, extra_args: tuple = ()) -> dict:
    port = free_port()
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", env.get("BENCH_PLATFORM", "cpu"))
    for k in ("IMAGINARY_TPU_WORKER", "IMAGINARY_TPU_WORKER_EPOCH"):
        env.pop(k, None)
    fleet_path = None
    args = [sys.executable, "-m", "imaginary_tpu.cli", "--workers", str(n),
            "--port", str(port), "--enable-url-source",
            "--cache-result-mb", "32"]
    if shm_on:
        fd, fleet_path = tempfile.mkstemp(prefix="bench-fleet-",
                                          suffix=".shm")
        os.close(fd)
        os.unlink(fleet_path)
        env["IMAGINARY_TPU_FLEET_PATH"] = fleet_path
        args += ["--fleet-cache-mb", "64"]
        args += list(extra_args)
    else:
        env.pop("IMAGINARY_TPU_FLEET_PATH", None)
    sup = subprocess.Popen(args, env=env, stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL)
    try:
        _wait_healthy(port)
        urls = [f"http://127.0.0.1:{port}/resize?width=300&height=200"
                f"&url={origin_base}/img/{i}" for i in seq]
        # warm ONLY the boot/compile path (one dedicated URL outside the
        # measured set): the measured window starts with cold result
        # caches in both arms, so the miss traffic — where the shm tier
        # earns its keep — is what gets measured
        warm_url = (f"http://127.0.0.1:{port}/resize?width=300&height=200"
                    f"&url={origin_base}/img/{SHM_AB_URLS}")

        def one(k, i, _urls=urls):
            req = urllib.request.Request(_urls[i % len(_urls)],
                                         headers={"Connection": "close"})
            with urllib.request.urlopen(req, timeout=120) as r:
                r.read()
                assert r.status == 200

        def warm(k, i):
            one(k, 0, _urls=[warm_url])

        run_workers(warm, max(4.0, duration / 3), n_threads)
        rate, lats = run_workers(one, duration, n_threads)
        counters = _sum_fleet_counters(port) if shm_on else {}
        return {"rate": rate, "p50_ms": pctl(lats, 0.50),
                "p99_ms": pctl(lats, 0.99), "fleet": counters}
    finally:
        sup.send_signal(signal.SIGTERM)
        try:
            sup.wait(timeout=30)
        except subprocess.TimeoutExpired:
            sup.kill()
            sup.wait()
        if fleet_path and os.path.exists(fleet_path):
            try:
                os.unlink(fleet_path)
            except OSError:
                pass


def shm_ab(duration: float, n_threads: int, n: int = 2) -> int:
    base = make_1080p_jpeg()
    # +1: the last variant is the warmup-only URL (boot/compile), never
    # part of the measured zipf set
    variants = [base + b"\x00" * (i + 1) for i in range(SHM_AB_URLS + 1)]
    origin, origin_base = _start_origin(variants)
    try:
        seq = _zipf_seq(20_000, SHM_AB_URLS, SHM_AB_ZIPF)
        arms = []
        for shm_on in (False, True, True, False):  # ABBA: drift cancels
            arms.append(_shm_arm(n, origin_base, seq, duration, n_threads,
                                 shm_on=shm_on))
    finally:
        origin.shutdown()
    off_rate = (arms[0]["rate"] + arms[3]["rate"]) / 2.0
    on_rate = (arms[1]["rate"] + arms[2]["rate"]) / 2.0
    off = {"rate": off_rate,
           "p99_ms": max(arms[0]["p99_ms"], arms[3]["p99_ms"])}
    on = {"rate": on_rate, "p99_ms": max(arms[1]["p99_ms"],
                                         arms[2]["p99_ms"])}
    fleet = {k: arms[1]["fleet"].get(k, 0) + arms[2]["fleet"].get(k, 0)
             for k in ("hits", "misses", "publishes", "corrupt",
                       "corrupt_served")}
    lookups = fleet.get("hits", 0) + fleet.get("misses", 0)
    cross_ratio = round(fleet.get("hits", 0) / lookups, 4) if lookups else 0.0
    ratio = round(on["rate"] / off["rate"], 3) if off["rate"] else 0.0
    row = {
        "metric": "workers_shm_cache_ab",
        "workers": n,
        "unit": "req/sec",
        "independent_caches": round(off["rate"], 2),
        "shm_tier": round(on["rate"], 2),
        "ratio": ratio,
        "p99_ms_independent": off["p99_ms"],
        "p99_ms_shm": on["p99_ms"],
        "cross_worker_hits": fleet.get("hits", 0),
        "cross_worker_hit_ratio": cross_ratio,
        "shm_publishes": fleet.get("publishes", 0),
        "corrupt_served": fleet.get("corrupt_served", 0),
        "cpus": os.cpu_count() or 1,
    }
    print(json.dumps(row), flush=True)
    fails = []
    if off["rate"] == 0 or on["rate"] == 0:
        fails.append("an arm produced zero requests")
    if fleet.get("hits", 0) == 0:
        fails.append("shm tier never produced a cross-worker hit")
    if fleet.get("corrupt_served", 0):
        fails.append("corrupt bytes served from the shm tier")
    if ratio < 1.0:
        fails.append(f"shm tier LOST to independent caches ({ratio}x)")
    if fails:
        for f in fails:
            print(f"[workers] SHM A/B FAIL: {f}", file=sys.stderr)
        return 1
    print(f"[workers] SHM A/B PASS: {off['rate']:.1f} -> {on['rate']:.1f} "
          f"req/s ({ratio}x) at N={n}, cross-worker hit ratio "
          f"{cross_ratio}", file=sys.stderr)
    return 0


# --- fleet coherence rows (ISSUE 19) -----------------------------------------

_R19_ARTIFACT = os.path.join("artifacts", "bench_workers_r19_cpu.jsonl")

COHERENCE_ARGS = ("--fleet-coherence", "--cache-coalesce",
                  "--fleet-hop-ms", "15000")


def _archive_r19(row: dict) -> None:
    try:
        os.makedirs("artifacts", exist_ok=True)
        with open(_R19_ARTIFACT, "a") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")
    except OSError as e:
        print(f"[workers] WARN: could not archive to {_R19_ARTIFACT}: {e}",
              file=sys.stderr)


def fleet_coalesce_gate(n: int = 2, clients: int = 12) -> int:
    """THE singleflight gate: a cold fleet takes `clients` CONCURRENT
    IDENTICAL requests and must execute the pipeline exactly ONCE
    fleet-wide — local coalescing collapses each worker's copies, the
    forward hop routes every worker to the digest's owner, and the claim
    table guarantees the owner runs once. Metered by the publish delta:
    every execution deposits exactly one shm entry; waiters and
    forwarded serves deposit nothing."""
    base = make_1080p_jpeg()
    variants = [base + b"\x00", base + b"\x00\x00"]
    origin, origin_base = _start_origin(variants)
    port = free_port()
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", env.get("BENCH_PLATFORM", "cpu"))
    for k in ("IMAGINARY_TPU_WORKER", "IMAGINARY_TPU_WORKER_EPOCH"):
        env.pop(k, None)
    fd, fleet_path = tempfile.mkstemp(prefix="bench-fleet-", suffix=".shm")
    os.close(fd)
    os.unlink(fleet_path)
    env["IMAGINARY_TPU_FLEET_PATH"] = fleet_path
    args = [sys.executable, "-m", "imaginary_tpu.cli", "--workers", str(n),
            "--port", str(port), "--enable-url-source",
            "--cache-result-mb", "32", "--fleet-cache-mb", "64",
            "--request-timeout", "60"] + list(COHERENCE_ARGS)
    sup = subprocess.Popen(args, env=env, stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL)
    errs: list = []
    try:
        _wait_healthy(port)
        # warm BOTH workers' compile ladders on the warm-only URL (the
        # kernel spreads fresh connections; 3x clients reaches both)
        warm_url = (f"http://127.0.0.1:{port}/resize?width=300&height=200"
                    f"&url={origin_base}/img/0")
        for _ in range(3 * clients):
            req = urllib.request.Request(warm_url,
                                         headers={"Connection": "close"})
            with urllib.request.urlopen(req, timeout=120) as r:
                r.read()
        before = _sum_fleet_counters(port)
        url = (f"http://127.0.0.1:{port}/resize?width=300&height=200"
               f"&url={origin_base}/img/1")
        barrier = threading.Barrier(clients)

        def one():
            try:
                barrier.wait(timeout=60)
                req = urllib.request.Request(url,
                                             headers={"Connection": "close"})
                with urllib.request.urlopen(req, timeout=120) as r:
                    if r.status != 200 or not r.read():
                        errs.append("bad response")
            except Exception as e:  # the gate reports, never hangs
                errs.append(repr(e))

        threads = [threading.Thread(target=one) for _ in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        after = _sum_fleet_counters(port)
    finally:
        sup.send_signal(signal.SIGTERM)
        try:
            sup.wait(timeout=30)
        except subprocess.TimeoutExpired:
            sup.kill()
            sup.wait()
        origin.shutdown()
        if os.path.exists(fleet_path):
            try:
                os.unlink(fleet_path)
            except OSError:
                pass
    executed = after.get("publishes", 0) - before.get("publishes", 0)
    row = {
        "metric": "workers_fleet_coalesce",
        "workers": n,
        "clients": clients,
        "executions": executed,
        "errors": len(errs),
        "coh_forwards": after.get("coh_forwards", 0),
        "coh_serve_forwarded": after.get("coh_serve_forwarded", 0),
        "coh_waiter_hits": after.get("coh_waiter_hits", 0),
        "cpus": os.cpu_count() or 1,
    }
    print(json.dumps(row), flush=True)
    _archive_r19(row)
    fails = []
    if errs:
        fails.append(f"{len(errs)} of {clients} concurrent requests "
                     f"failed: {errs[:3]}")
    if executed != 1:
        fails.append(f"{clients} identical concurrent requests executed "
                     f"{executed} times fleet-wide (want exactly 1)")
    if fails:
        for f in fails:
            print(f"[workers] FLEET COALESCE FAIL: {f}", file=sys.stderr)
        return 1
    print(f"[workers] FLEET COALESCE PASS: {clients} concurrent identical "
          f"requests -> 1 execution fleet-wide at N={n}", file=sys.stderr)
    return 0


def coherence_ab(duration: float, n_threads: int, n: int = 2) -> int:
    """Coherence on/off zipf A/B over the same shm-tiered fleet. The
    claim: digest ownership turns cold cross-worker traffic into served
    traffic — a non-owner's miss rides the hop to the owner instead of
    recomputing. Cross-worker service ratio = (shm hits + forwarded
    serves) / shm lookups; every forward follows a local shm miss, so
    the ratio stays <= 1 and the OFF arm's forwards are zero by
    construction."""
    base = make_1080p_jpeg()
    variants = [base + b"\x00" * (i + 1) for i in range(SHM_AB_URLS + 1)]
    origin, origin_base = _start_origin(variants)
    try:
        seq = _zipf_seq(20_000, SHM_AB_URLS, SHM_AB_ZIPF)
        arms = []
        for coh_on in (False, True, True, False):  # ABBA: drift cancels
            arms.append(_shm_arm(
                n, origin_base, seq, duration, n_threads, shm_on=True,
                extra_args=COHERENCE_ARGS if coh_on else ()))
    finally:
        origin.shutdown()
    off_rate = (arms[0]["rate"] + arms[3]["rate"]) / 2.0
    on_rate = (arms[1]["rate"] + arms[2]["rate"]) / 2.0
    on_fleet = {k: arms[1]["fleet"].get(k, 0) + arms[2]["fleet"].get(k, 0)
                for k in ("hits", "misses", "publishes", "corrupt_served",
                          "coh_forwards", "coh_serve_forwarded",
                          "coh_waiter_hits", "coh_local_fallbacks")}
    # client-side lookups only: a forwarded request books a SECOND shm
    # lookup on the owner while serving the hop (one client request, two
    # processes), so the owner-side share — one lookup per forwarded
    # serve — comes out of the denominator. The ratio reads: of the
    # requests that missed their local LRU, what fraction the fleet
    # served without a local recompute (shm hit or owner forward).
    lookups = (on_fleet["hits"] + on_fleet["misses"]
               - on_fleet["coh_serve_forwarded"])
    cross = (on_fleet["hits"] + on_fleet["coh_forwards"]) / lookups \
        if lookups > 0 else 0.0
    ratio = round(on_rate / off_rate, 3) if off_rate else 0.0
    row = {
        "metric": "workers_coherence_ab",
        "workers": n,
        "unit": "req/sec",
        "coherence_off": round(off_rate, 2),
        "coherence_on": round(on_rate, 2),
        "ratio": ratio,
        "cross_worker_hit_ratio": round(cross, 4),
        "shm_hits": on_fleet["hits"],
        "forwards": on_fleet["coh_forwards"],
        "serve_forwarded": on_fleet["coh_serve_forwarded"],
        "waiter_hits": on_fleet["coh_waiter_hits"],
        "local_fallbacks": on_fleet["coh_local_fallbacks"],
        "corrupt_served": on_fleet["corrupt_served"],
        "cpus": os.cpu_count() or 1,
    }
    print(json.dumps(row), flush=True)
    _archive_r19(row)
    fails = []
    if off_rate == 0 or on_rate == 0:
        fails.append("an arm produced zero requests")
    if on_fleet["coh_forwards"] == 0:
        fails.append("coherence arm never took the forward hop")
    if cross <= 0.458:
        fails.append(f"cross-worker hit ratio {cross:.4f} <= 0.458 with "
                     "coherence on")
    if on_fleet["corrupt_served"]:
        fails.append("corrupt bytes served")
    if fails:
        for f in fails:
            print(f"[workers] COHERENCE A/B FAIL: {f}", file=sys.stderr)
        return 1
    print(f"[workers] COHERENCE A/B PASS: {off_rate:.1f} -> {on_rate:.1f} "
          f"req/s ({ratio}x) at N={n}, cross-worker hit ratio "
          f"{cross:.4f} (> 0.458)", file=sys.stderr)
    return 0


# --- multi-host rows (ISSUE 20) ----------------------------------------------

_R20_ARTIFACT = os.path.join("artifacts", "bench_workers_r20_cpu.jsonl")


def _archive_r20(row: dict) -> None:
    try:
        os.makedirs("artifacts", exist_ok=True)
        with open(_R20_ARTIFACT, "a") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")
    except OSError as e:
        print(f"[workers] WARN: could not archive to {_R20_ARTIFACT}: {e}",
              file=sys.stderr)


def _start_mh_host(n: int, port: int, admin_port: int, peer_admin: int,
                   host_id: str, router: bool, probe_interval: float = 2.0,
                   extra_args: tuple = ()) -> tuple:
    """One host of a 2-host cluster: its own supervisor, shm file, admin
    plane and host identity, --peers pointed at the other host's admin."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", env.get("BENCH_PLATFORM", "cpu"))
    for k in ("IMAGINARY_TPU_WORKER", "IMAGINARY_TPU_WORKER_EPOCH",
              "IMAGINARY_TPU_HOST_ID", "IMAGINARY_TPU_HOST_EPOCH"):
        env.pop(k, None)
    fd, fleet_path = tempfile.mkstemp(prefix=f"bench-mh-{host_id}-",
                                      suffix=".shm")
    os.close(fd)
    os.unlink(fleet_path)
    env["IMAGINARY_TPU_FLEET_PATH"] = fleet_path
    args = [sys.executable, "-m", "imaginary_tpu.cli", "--workers", str(n),
            "--port", str(port), "--enable-url-source",
            "--cache-result-mb", "32", "--fleet-cache-mb", "64",
            "--request-timeout", "60", "--host-id", host_id,
            "--fleet-admin-port", str(admin_port),
            "--peers", f"http://127.0.0.1:{peer_admin}",
            "--peer-probe-interval", str(probe_interval)]
    if router:
        args.append("--router")
    args += list(extra_args)
    sup = subprocess.Popen(args, env=env, stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL)
    return sup, fleet_path


def _stop_host(sup, fleet_path: str) -> None:
    sup.send_signal(signal.SIGTERM)
    try:
        sup.wait(timeout=30)
    except subprocess.TimeoutExpired:
        sup.kill()
        sup.wait()
    if fleet_path and os.path.exists(fleet_path):
        try:
            os.unlink(fleet_path)
        except OSError:
            pass


def _wait_cluster(admin_port: int, peer_id: str,
                  deadline_s: float = 60.0) -> None:
    """Block until this host's merged /fleetz?scope=cluster shows the
    peer host alive (gossip has crossed at least once each way)."""
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{admin_port}/fleetz?scope=cluster",
                    timeout=2) as r:
                view = json.loads(r.read())
            if view.get("hosts", {}).get(peer_id, {}).get("alive"):
                return
        except Exception:
            pass
        time.sleep(0.3)
    raise RuntimeError(f"cluster view never showed {peer_id} alive")


def _sum_multihost_counters(port: int, samples: int = 30) -> dict:
    """Sum the per-worker router stats from /health (latest per pid)."""
    per_pid: dict = {}
    for _ in range(samples):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=2) as r:
                h = json.loads(r.read())
            if "multihost" in h:
                per_pid[h["pid"]] = h["multihost"]
        except Exception:
            time.sleep(0.1)
    out = {}
    for k in ("forwards", "forward_fails", "served_for_peer", "spills",
              "local_fallbacks"):
        out[k] = sum(v.get(k, 0) for v in per_pid.values())
    return out


def multihost_ab(duration: float, n_threads: int, n: int = 2) -> int:
    """2-host scale-out A/B: one 2-worker host vs a 2-host cluster of
    the same hosts (gossip armed, router off — pure capacity), clients
    round-robined across hosts, same paced zipf workload. The ISSUE 20
    acceptance (>= 1.7x) binds on hosts with enough cores to offer real
    parallel capacity; on smaller hosts the row reports the mechanism's
    cost and gates only on correctness."""
    base = make_1080p_jpeg()
    variants = [base + b"\x00" * (i + 1) for i in range(SHM_AB_URLS + 1)]
    origin, origin_base = _start_origin(variants)
    seq = _zipf_seq(20_000, SHM_AB_URLS, SHM_AB_ZIPF)
    try:
        # arm 1: the single-host headline (shm tier on, same flags)
        single = _shm_arm(n, origin_base, seq, duration, n_threads,
                          shm_on=True)

        # arm 2: two such hosts, gossip crossed, clients split evenly
        ports = [free_port(), free_port()]
        admins = [free_port(), free_port()]
        hosts = []
        try:
            for i in range(2):
                # production gossip cadence (2 s): every /fleetz poll
                # scrapes this host's workers, so a faster cadence would
                # tax the measured arm with scrape traffic
                hosts.append(_start_mh_host(
                    n, ports[i], admins[i], admins[1 - i],
                    f"bench-host-{i}", router=False))
            for port in ports:
                _wait_healthy(port)
            _wait_cluster(admins[0], "bench-host-1")
            _wait_cluster(admins[1], "bench-host-0")
            urls = {port: [f"http://127.0.0.1:{port}/resize?width=300"
                           f"&height=200&url={origin_base}/img/{i}"
                           for i in seq] for port in ports}
            warm = {port: (f"http://127.0.0.1:{port}/resize?width=300"
                           f"&height=200&url={origin_base}/img/"
                           f"{SHM_AB_URLS}") for port in ports}

            def one(k, i):
                port = ports[k % 2]  # half the clients per host
                req = urllib.request.Request(
                    urls[port][i % len(urls[port])],
                    headers={"Connection": "close"})
                with urllib.request.urlopen(req, timeout=120) as r:
                    r.read()
                    assert r.status == 200

            def warm_one(k, i):
                req = urllib.request.Request(
                    warm[ports[k % 2]], headers={"Connection": "close"})
                with urllib.request.urlopen(req, timeout=120) as r:
                    r.read()

            # twice the single-host warm: 2x the workers means 2x the
            # compile ladders to absorb before the measured window
            run_workers(warm_one, 2 * max(4.0, duration / 3), n_threads)
            rate, lats = run_workers(one, duration, n_threads)
        finally:
            for sup, path in hosts:
                _stop_host(sup, path)
    finally:
        origin.shutdown()
    cpus = os.cpu_count() or 1
    ratio = round(rate / single["rate"], 3) if single["rate"] else 0.0
    # 2 hosts x n workers need their own cores before scale-out can
    # show: bind the hard gate where the capacity exists
    gate_binds = cpus >= 2 * n
    row = {
        "metric": "workers_multihost_ab",
        "hosts": 2,
        "workers_per_host": n,
        "unit": "req/sec",
        "single_host": round(single["rate"], 2),
        "two_hosts": round(rate, 2),
        "ratio": ratio,
        "p99_ms_single": single["p99_ms"],
        "p99_ms_two_hosts": pctl(lats, 0.99),
        "gate_binds": gate_binds,
        "cpus": cpus,
    }
    print(json.dumps(row), flush=True)
    _archive_r20(row)
    fails = []
    if single["rate"] == 0 or rate == 0:
        fails.append("an arm produced zero requests")
    if gate_binds and ratio < 1.7:
        fails.append(f"2-host cluster only {ratio}x the single host on "
                     f"{cpus} cpus (acceptance >= 1.7x)")
    # below 2n cores the ratio is advisory (bench_n precedent): the
    # 2-host arm pays duplicated compute on a serialized core, so only
    # outright collapse — an arm that stopped serving — fails the row
    if fails:
        for f in fails:
            print(f"[workers] MULTIHOST A/B FAIL: {f}", file=sys.stderr)
        return 1
    binds = "binding" if gate_binds else f"advisory on {cpus} cpu(s)"
    print(f"[workers] MULTIHOST A/B PASS: {single['rate']:.1f} -> "
          f"{rate:.1f} req/s ({ratio}x, gate {binds})", file=sys.stderr)
    return 0


def multihost_coalesce_gate(n: int = 2, clients: int = 12) -> int:
    """Cross-host singleflight: the same cold digest offered to BOTH
    hosts of a routed cluster concurrently must execute the pipeline
    exactly once CLUSTER-wide — the non-owner host forwards its share
    one hop to the owner, whose fleet coherence collapses the rest.
    Metered by the publish delta summed over both hosts' shm tiers."""
    base = make_1080p_jpeg()
    variants = [base + b"\x00", base + b"\x00\x00"]
    origin, origin_base = _start_origin(variants)
    ports = [free_port(), free_port()]
    admins = [free_port(), free_port()]
    hosts = []
    errs: list = []
    try:
        for i in range(2):
            hosts.append(_start_mh_host(
                n, ports[i], admins[i], admins[1 - i], f"coal-host-{i}",
                router=True, probe_interval=0.3,
                extra_args=COHERENCE_ARGS))
        for port in ports:
            _wait_healthy(port)
        _wait_cluster(admins[0], "coal-host-1")
        _wait_cluster(admins[1], "coal-host-0")
        # the WORKERS' own gossip tables ride the same 0.3 s cadence as
        # the supervisors'; give them a couple of beats past convergence
        time.sleep(1.5)
        for port in ports:
            warm_url = (f"http://127.0.0.1:{port}/resize?width=300"
                        f"&height=200&url={origin_base}/img/0")
            for _ in range(3 * clients // 2):
                req = urllib.request.Request(
                    warm_url, headers={"Connection": "close"})
                with urllib.request.urlopen(req, timeout=120) as r:
                    r.read()
        before = sum(_sum_fleet_counters(p).get("publishes", 0)
                     for p in ports)
        barrier = threading.Barrier(clients)

        def one(port):
            try:
                barrier.wait(timeout=60)
                url = (f"http://127.0.0.1:{port}/resize?width=300"
                       f"&height=200&url={origin_base}/img/1")
                req = urllib.request.Request(
                    url, headers={"Connection": "close"})
                with urllib.request.urlopen(req, timeout=120) as r:
                    if r.status != 200 or not r.read():
                        errs.append("bad response")
            except Exception as e:
                errs.append(repr(e))

        threads = [threading.Thread(target=one, args=(ports[j % 2],))
                   for j in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        after = sum(_sum_fleet_counters(p).get("publishes", 0)
                    for p in ports)
        mh = {p: _sum_multihost_counters(p) for p in ports}
    finally:
        for sup, path in hosts:
            _stop_host(sup, path)
        origin.shutdown()
    executed = after - before
    cross = sum(m["forwards"] + m["served_for_peer"] for m in mh.values())
    row = {
        "metric": "workers_multihost_coalesce",
        "hosts": 2,
        "workers_per_host": n,
        "clients": clients,
        "executions": executed,
        "errors": len(errs),
        "host_forwards": sum(m["forwards"] for m in mh.values()),
        "served_for_peer": sum(m["served_for_peer"] for m in mh.values()),
        "forward_fails": sum(m["forward_fails"] for m in mh.values()),
        "cpus": os.cpu_count() or 1,
    }
    print(json.dumps(row), flush=True)
    _archive_r20(row)
    fails = []
    if errs:
        fails.append(f"{len(errs)} of {clients} concurrent requests "
                     f"failed: {errs[:3]}")
    if executed != 1:
        fails.append(f"{clients} identical requests across 2 hosts "
                     f"executed {executed} times cluster-wide (want 1)")
    if cross == 0:
        fails.append("no request ever crossed hosts (router idle — the "
                     "row proved nothing)")
    if fails:
        for f in fails:
            print(f"[workers] MULTIHOST COALESCE FAIL: {f}",
                  file=sys.stderr)
        return 1
    print(f"[workers] MULTIHOST COALESCE PASS: {clients} concurrent "
          f"identical requests across 2 hosts -> 1 execution, "
          f"{row['host_forwards']} cross-host forward(s)", file=sys.stderr)
    return 0


def main() -> None:
    duration = float(os.environ.get("BENCH_DURATION", "12"))
    n_threads = int(os.environ.get("BENCH_THREADS", "16"))
    if os.environ.get("BENCH_COHERENCE_ONLY", "0") == "1":
        # the r19 gate subset: fleet singleflight + coherence A/B only
        rc = fleet_coalesce_gate()
        rc = coherence_ab(duration, n_threads) or rc
        if rc:
            raise SystemExit(rc)
        return
    if os.environ.get("BENCH_MULTIHOST_ONLY", "0") == "1":
        # the r20 gate subset: cross-host singleflight + 2-host A/B
        rc = multihost_coalesce_gate()
        rc = multihost_ab(duration, n_threads) or rc
        if rc:
            raise SystemExit(rc)
        return
    counts = [int(x) for x in os.environ.get("BENCH_WORKERS", "1 2").split()]
    body = make_1080p_jpeg()
    results = []
    for n in counts:
        res = bench_n(n, body, duration, n_threads)
        results.append(res)
        print(f"[workers] N={n}: {res['value']} req/s "
              f"p50={res['p50_ms']}ms p99={res['p99_ms']}ms", file=sys.stderr)
        print(json.dumps(res), flush=True)
    if len(results) >= 2 and results[0]["value"] > 0:
        ratio = results[1]["value"] / results[0]["value"]
        print(f"[workers] N={counts[1]}/N={counts[0]} ratio: {ratio:.2f}x "
              f"on a {os.cpu_count()}-core host", file=sys.stderr)
    if os.environ.get("BENCH_SHM_AB", "1") != "0":
        if shm_ab(duration, n_threads) != 0:
            raise SystemExit(1)
    if os.environ.get("BENCH_COHERENCE", "1") != "0":
        rc = fleet_coalesce_gate()
        rc = coherence_ab(duration, n_threads) or rc
        if rc:
            raise SystemExit(1)
    if os.environ.get("BENCH_MULTIHOST", "1") != "0":
        rc = multihost_coalesce_gate()
        rc = multihost_ab(duration, n_threads) or rc
        if rc:
            raise SystemExit(1)


if __name__ == "__main__":
    main()
