#!/usr/bin/env python
"""Open-loop (fixed-rate) latency harness — the vegeta analogue.

The reference ships `benchmark.sh` (vegeta: 50 rps x 30 s POST of a 1080p
JPEG against /crop, /resize, /extract — /root/reference/benchmark.sh:16-31).
This harness reproduces that shape against OUR live HTTP server, plus the
4-op /pipeline chain of BASELINE.json config #3, and reports p50/p95/p99
per route. Open-loop means requests fire on a fixed clock regardless of
completions, so queueing delay shows up in the tail. The offered rate per
route is the requested rate CAPPED at ~70% of the host's measured serial
service rate: above saturation an open-loop clock measures unbounded
queue growth, not service latency. Both rates are recorded in the JSON
(rate_rps = offered, rate_requested_rps = asked), so a PASS at a reduced
operating point is always visible as such.

Usage:
    python bench_latency.py                # 20 rps x 15 s per route
    BENCH_RATE=50 BENCH_SECS=30 python bench_latency.py

Output: one JSON line per route on stdout; human detail on stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

import numpy as np

from urllib.parse import quote

ROUTES = [
    # (name, path+query, method) — the reference's vegeta trio (benchmark.sh)
    ("resize", "/resize?width=300&height=200", "POST"),
    ("crop", "/crop?width=400&height=300", "POST"),
    ("extract", "/extract?top=100&left=100&areawidth=600&areaheight=400", "POST"),
    # the reference's documented WORST op ("enlarge degrades under
    # >20 req/s", README.md:306): 1080p -> 2560x1440 upscale
    ("enlarge", "/enlarge?width=2560&height=1440", "POST"),
    # same op PINNED to the host interpreter (a second app instance with
    # force_host=True): prices the spill path's separable resample itself,
    # independent of whatever mix the cost model chooses — the row the
    # r5 FAIL (p99 181 ms vs the 45.4 ms 2x-cv2 bar) is graded on
    ("enlarge_host", "/enlarge?width=2560&height=1440", "POST"),
    (
        "pipeline",
        "/pipeline?operations=" + quote(
            json.dumps(
                [
                    {"operation": "crop", "params": {"width": 1600, "height": 900}},
                    {"operation": "resize", "params": {"width": 640}},
                    {"operation": "blur", "params": {"sigma": 1.5}},
                    {"operation": "convert", "params": {"type": "jpeg"}},
                ]
            )
        ),
        "POST",
    ),
]

# BASELINE.json config #2: mixed thumbnail/crop/rotate traffic. Each request
# in the run round-robins the three routes (a multi-chain load that stresses
# batch formation across jit-cache keys).
MIXED_ROUTES = [
    "/thumbnail?width=150",
    "/crop?width=400&height=300",
    "/rotate?rotate=90",
]

# BASELINE.json config #3: [resize, blur, watermark, convert->webp] on 4K PNG.
PIPELINE_4K = "/pipeline?operations=" + quote(
    json.dumps(
        [
            {"operation": "resize", "params": {"width": 1280}},
            {"operation": "blur", "params": {"sigma": 1.2}},
            {"operation": "watermark", "params": {"text": "bench", "opacity": 0.5}},
            {"operation": "convert", "params": {"type": "webp"}},
        ]
    )
)


def _make_4k_png() -> bytes:
    import cv2

    yy, xx = np.mgrid[0:2160, 0:3840]
    img = np.stack(
        [
            (xx % 256).astype(np.uint8),
            (yy % 256).astype(np.uint8),
            ((xx // 16 + yy // 16) % 256).astype(np.uint8),
        ],
        axis=-1,
    )
    ok, out = cv2.imencode(".png", img)
    assert ok
    return out.tobytes()


from bench_util import make_1080p_jpeg as _make_1080p_jpeg  # noqa: E402


from bench_util import pctl as _pctl  # noqa: E402


async def _fire(session, url, method, body, lats, errors, marks, t_start):
    t0 = time.monotonic()
    try:
        async with session.request(method, url, data=body) as resp:
            await resp.read()
            if resp.status != 200:
                errors.append(resp.status)
                return
    except Exception:
        errors.append(-1)
        return
    t1 = time.monotonic()
    lats.append((t1 - t0) * 1000.0)
    marks.append((t0 - t_start, (t1 - t0) * 1000.0))


async def run_route(base, name, pathq, method, body, rate, secs):
    """pathq may be a single path or a list (round-robined per request —
    the mixed-traffic shape of BASELINE.json config #2)."""
    import aiohttp

    paths = pathq if isinstance(pathq, list) else [pathq]
    lats: list = []
    errors: list = []
    marks: list = []  # (send-offset s, latency ms) for straggler forensics
    interval = 1.0 / rate
    n = int(rate * secs)
    conn = aiohttp.TCPConnector(limit=0)
    async with aiohttp.ClientSession(connector=conn) as session:
        tasks = []
        t_start = time.monotonic()
        for i in range(n):
            # fixed-clock schedule: sleep until this request's slot
            slot = t_start + i * interval
            delay = slot - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(
                asyncio.create_task(
                    _fire(session, base + paths[i % len(paths)], method, body,
                          lats, errors, marks, t_start)
                )
            )
        await asyncio.gather(*tasks)
    # The p99 verdict on a 300-request window is set by its ~3 slowest
    # requests; print WHEN they were sent so a tail can be told apart
    # (cluster at one instant = one stall event — GC, probe, compile;
    # spread uniformly = steady-state service variance).
    worst = sorted(marks, key=lambda m: -m[1])[:5]
    print(f"[lat]   {name} stragglers: "
          + ", ".join(f"{lat:.1f}ms@{off:.2f}s" for off, lat in worst),
          file=sys.stderr)
    sent = n
    ok = len(lats)
    res = {
        "metric": f"latency_{name}",
        "rate_rps": rate,
        "duration_s": secs,
        "sent": sent,
        "ok": ok,
        "errors": len(errors),
        "p50_ms": _pctl(lats, 0.50),
        "p95_ms": _pctl(lats, 0.95),
        "p99_ms": _pctl(lats, 0.99),
        "mean_ms": round(sum(lats) / ok, 2) if ok else 0.0,
    }
    return res


def _cv2_workloads(buf_1080: bytes, buf_4k) -> dict:
    """Per-scenario cv2 equivalents — the honest '1x' each scenario's
    p99 <= 2x-baseline verdict is measured against (comparing a 4-op 4K-PNG
    pipeline to a single 1080p resize would grade apples against oranges)."""
    import cv2

    d1080 = np.frombuffer(buf_1080, np.uint8)
    jq = [int(cv2.IMWRITE_JPEG_QUALITY), 80]

    def resize():
        a = cv2.imdecode(d1080, cv2.IMREAD_COLOR)
        cv2.imencode(".jpg", cv2.resize(a, (300, 200), interpolation=cv2.INTER_AREA), jq)

    def crop():  # resize-to-cover then centre-crop (bimg crop semantics)
        a = cv2.imdecode(d1080, cv2.IMREAD_COLOR)
        h, w = a.shape[:2]
        s = max(400 / w, 300 / h)
        r = cv2.resize(a, (round(w * s), round(h * s)), interpolation=cv2.INTER_AREA)
        t, l = (r.shape[0] - 300) // 2, (r.shape[1] - 400) // 2
        cv2.imencode(".jpg", r[t : t + 300, l : l + 400], jq)

    def extract():
        a = cv2.imdecode(d1080, cv2.IMREAD_COLOR)
        cv2.imencode(".jpg", a[100:500, 100:700], jq)

    def enlarge():
        a = cv2.imdecode(d1080, cv2.IMREAD_COLOR)
        cv2.imencode(".jpg", cv2.resize(a, (2560, 1440),
                                        interpolation=cv2.INTER_CUBIC), jq)

    enlarge_host = enlarge  # same honest 1x: the op, not the placement

    def pipeline():
        a = cv2.imdecode(d1080, cv2.IMREAD_COLOR)
        h, w = a.shape[:2]
        t, l = (h - 900) // 2, (w - 1600) // 2
        a = a[t : t + 900, l : l + 1600]
        a = cv2.resize(a, (640, 360), interpolation=cv2.INTER_AREA)
        a = cv2.GaussianBlur(a, (0, 0), 1.5)
        cv2.imencode(".jpg", a, jq)

    def mixed():  # one thumbnail + one crop + one rotate, averaged by /3
        a = cv2.imdecode(d1080, cv2.IMREAD_COLOR)
        cv2.imencode(".jpg", cv2.resize(a, (150, 84), interpolation=cv2.INTER_AREA), jq)
        crop()
        a = cv2.imdecode(d1080, cv2.IMREAD_COLOR)
        cv2.imencode(".jpg", cv2.rotate(a, cv2.ROTATE_90_CLOCKWISE), jq)

    out = {
        "resize": (resize, 1.0),
        "crop": (crop, 1.0),
        "extract": (extract, 1.0),
        "enlarge": (enlarge, 1.0),
        "enlarge_host": (enlarge_host, 1.0),
        "pipeline": (pipeline, 1.0),
        "mixed_thumb_crop_rotate": (mixed, 3.0),  # 3 requests per call
    }
    if buf_4k is not None:
        d4k = np.frombuffer(buf_4k, np.uint8)

        def pipeline_4k():
            a = cv2.imdecode(d4k, cv2.IMREAD_COLOR)
            a = cv2.resize(a, (1280, 720), interpolation=cv2.INTER_AREA)
            a = cv2.GaussianBlur(a, (0, 0), 1.2)
            cv2.putText(a, "bench", (20, 40), cv2.FONT_HERSHEY_SIMPLEX, 1.0,
                        (255, 255, 255), 2)
            cv2.imencode(".webp", a, [int(cv2.IMWRITE_WEBP_QUALITY), 80])

        out["pipeline_4k_png"] = (pipeline_4k, 1.0)
    return out


def baseline_latency(fn, per_call: float = 1.0, n: int = 40,
                     windows: int = 3) -> dict:
    """cv2 latency distribution of one scenario-equivalent workload,
    MEDIANED across independent windows.

    A single window's bar swings up to 4x between runs on the shared
    1-CPU host (measured: pipeline baseline p99 11.9-49.1 ms across four
    same-day runs) while our own medianed body holds still — so verdicts
    were flipping on baseline noise, not on our latency. The bar is now
    medianed exactly the way `ours` is: per-window percentiles, median
    across windows; the per-window p99s ride along in the JSON so a
    noisy-host run is visible in the artifact."""
    fn()
    per = []
    for _ in range(max(1, windows)):
        lats = []
        for _ in range(n):
            t0 = time.monotonic()
            fn()
            lats.append((time.monotonic() - t0) * 1000.0 / per_call)
        per.append({"p50_ms": _pctl(lats, 0.50), "p99_ms": _pctl(lats, 0.99)})

    def med(k):
        vals = sorted(w[k] for w in per)
        return vals[len(vals) // 2]

    return {"p50_ms": med("p50_ms"), "p99_ms": med("p99_ms"),
            "window_p99s": [w["p99_ms"] for w in per]}


async def main_async():
    rate = float(os.environ.get("BENCH_RATE", "20"))
    secs = float(os.environ.get("BENCH_SECS", "15"))
    port = int(os.environ.get("BENCH_PORT", "8899"))

    platform = os.environ.get("BENCH_PLATFORM", "")
    if not platform:
        from bench_util import probe_accelerator

        if not probe_accelerator():
            # a dying tunnel hangs inside the runtime (measured): without
            # this gate the run is a 400-storm or a stall, not a benchmark
            print("[lat] *** ACCELERATOR UNREACHABLE - CPU-JAX FALLBACK; "
                  "this is NOT a TPU measurement ***", file=sys.stderr)
            platform = "cpu"
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    from aiohttp import web as aioweb

    from bench_util import ensure_native_built
    from imaginary_tpu.web.app import create_app, tune_gc_for_serving
    from imaginary_tpu.web.config import ServerOptions

    # the host-path rows measure the native separable resampler when it
    # can build here, the numpy tap fallback otherwise
    ensure_native_built()
    tune_gc_for_serving()  # measure the tuned serving process, like serve()
    o = ServerOptions(port=port)
    # access log to /dev/null: stdout must stay pure JSONL, and an
    # in-memory sink would grow unboundedly inside the measured process
    devnull = open(os.devnull, "w")
    app = create_app(o, log_stream=devnull)
    runner = aioweb.AppRunner(app)
    await runner.setup()
    site = aioweb.TCPSite(runner, "127.0.0.1", port)
    await site.start()

    # second instance, placement PINNED to the host interpreter: the
    # enlarge_host row prices the spill path itself (see ROUTES)
    o_host = ServerOptions(port=port + 1, force_host=True)
    app_host = create_app(o_host, log_stream=devnull)
    runner_host = aioweb.AppRunner(app_host)
    await runner_host.setup()
    await aioweb.TCPSite(runner_host, "127.0.0.1", port + 1).start()

    buf = _make_1080p_jpeg()
    base_url = f"http://127.0.0.1:{port}"
    host_base_url = f"http://127.0.0.1:{port + 1}"

    def scenario_base(name):
        return (host_base_url, app_host) if name == "enlarge_host" \
            else (base_url, app)

    only = os.environ.get("BENCH_ONLY", "")
    keep = {s.strip() for s in only.split(",") if s.strip()} if only else None
    want_4k = os.environ.get("BENCH_4K", "1") == "1" and (
        keep is None or "pipeline_4k_png" in keep
    )
    buf4k = _make_4k_png() if want_4k else None
    scenarios = [(n, p, m, buf, "1080p_jpeg") for n, p, m in ROUTES]
    scenarios.append(("mixed_thumb_crop_rotate", MIXED_ROUTES, "POST", buf, "1080p_jpeg"))
    if buf4k:
        scenarios.append(("pipeline_4k_png", PIPELINE_4K, "POST", buf4k, "4k_png"))
    if keep is not None:
        scenarios = [s for s in scenarios if s[0] in keep]

    # Warm every route's compile cache — including the batch-size ladder:
    # the executor pads micro-batches to powers of two, and each size is
    # its own XLA program. Without this, mid-run compiles (seconds each on
    # CPU) stall the fetch queue and the open-loop backlog snowballs into
    # queue-depth numbers that have nothing to do with service latency.
    import aiohttp

    serial_ms: dict = {}
    async with aiohttp.ClientSession() as s:

        async def once(base, p, body, method="POST"):
            async with s.request(method, base + p, data=body) as r:
                await r.read()
                return r.status

        for name, pathq, method, body, _inp in scenarios:
            base, _sapp = scenario_base(name)
            paths = pathq if isinstance(pathq, list) else [pathq]
            for p in paths:
                st = await once(base, p, body, method)
                if st != 200:
                    print(f"[lat] warmup {name} -> {st}", file=sys.stderr)
            for burst in (2, 4, 8, 16):
                sts = await asyncio.gather(
                    *(once(base, paths[i % len(paths)], body, method)
                      for i in range(burst))
                )
                bad = [s for s in sts if s != 200]
                if bad:
                    print(f"[lat] WARM FAILURE {name} burst={burst}: {bad} — "
                          f"route fails under concurrent load", file=sys.stderr)
            # calibrate: MEDIAN serial latency sets this route's offered
            # rate (a mean lets one straggler — a late compile, a cost-model
            # warmup ride — cut the offered rate several-fold)
            ts = []
            for i in range(5):
                t0 = time.monotonic()
                st = await once(base, paths[i % len(paths)], body, method)
                if st != 200:
                    print(f"[lat] WARM FAILURE {name} calibration -> {st}",
                          file=sys.stderr)
                ts.append((time.monotonic() - t0) * 1000.0)
            serial_ms[name] = sorted(ts)[len(ts) // 2]
            print(f"[lat] warm {name}: serial={serial_ms[name]:.1f}ms", file=sys.stderr)

    workloads = _cv2_workloads(buf, buf4k)
    if keep is not None:  # BENCH_ONLY: don't burn ~41 cv2 iterations per
        workloads = {n: w for n, w in workloads.items() if n in keep}  # unmeasured route
    # BENCH_BASELINE_PIN=<path>: persist the medianed bars per host so
    # repeat runs grade against ONE recorded baseline — a verdict flip
    # then requires OUR body to move, not the shared host's noise.
    pin = os.environ.get("BENCH_BASELINE_PIN", "")
    baselines = {}
    if pin and os.path.exists(pin):
        with open(pin) as f:
            baselines = {k: v for k, v in json.load(f).items() if k in workloads}
        print(f"[lat] cv2 baselines PINNED from {pin}: "
              f"{sorted(baselines)}", file=sys.stderr)
    missing = [n for n in workloads if n not in baselines]
    for name in missing:
        fn, per_call = workloads[name]
        baselines[name] = baseline_latency(fn, per_call)
        print(f"[lat] cv2 baseline[{name}]: p50={baselines[name]['p50_ms']}ms "
              f"p99={baselines[name]['p99_ms']}ms "
              f"(windows: {baselines[name]['window_p99s']})", file=sys.stderr)
    if pin and missing:
        merged = {}
        if os.path.exists(pin):
            with open(pin) as f:
                merged = json.load(f)
        merged.update({n: baselines[n] for n in missing})
        with open(pin, "w") as f:
            json.dump(merged, f, indent=1)
        print(f"[lat] wrote measured baselines to {pin}", file=sys.stderr)

    results = []
    for name, pathq, method, body, inp in scenarios:
        # Offered rate: the requested rate, capped at ~70% of this host's
        # serial service rate. An open-loop clock above saturation measures
        # unbounded queue growth, not the tail the p99 target is about; the
        # offered rate is recorded in the JSON so a FAIL at 20 rps and a
        # PASS at 3 rps are never conflated.
        route_rate = min(rate, max(0.5, 700.0 / max(serial_ms.get(name, 1.0), 1.0)))
        base, sapp = scenario_base(name)
        stats0 = sapp["service"].executor.stats.to_dict()
        res = await run_route(base, name, pathq, method, body, route_rate, secs)
        stats1 = sapp["service"].executor.stats.to_dict()
        delta = {k: round(stats1[k] - stats0[k], 3)
                 for k in ("items", "spilled", "shadow_probes", "groups")
                 if isinstance(stats1.get(k), (int, float))}
        # the spill path's own tail, from the executor's per-stage timing
        # (host_spill_p99_ms is cumulative over the run, not this window)
        delta["host_spill_p99_ms"] = stats1.get("host_spill_p99_ms", 0.0)
        print(f"[lat]   {name} executor delta: {delta}", file=sys.stderr)
        res["input"] = inp
        res["rate_requested_rps"] = rate
        base = baselines.get(name)
        if base:
            res["baseline_p99_ms"] = base["p99_ms"]
            if base.get("window_p99s"):
                res["baseline_window_p99s"] = base["window_p99s"]
            res["p99_vs_2x_baseline"] = (
                "PASS" if res["p99_ms"] <= 2 * base["p99_ms"] else "FAIL"
            )
        results.append(res)
        print(f"[lat] {name}: p50={res['p50_ms']} p95={res['p95_ms']} "
              f"p99={res['p99_ms']} ok={res['ok']}/{res['sent']} "
              f"({res.get('p99_vs_2x_baseline', 'n/a')} vs 2x baseline p99)",
              file=sys.stderr)

    await runner.cleanup()
    await runner_host.cleanup()
    import jax

    backend = jax.default_backend()
    for res in results:
        res["backend"] = backend
        print(json.dumps(res))


if __name__ == "__main__":
    asyncio.run(main_async())
