#!/usr/bin/env python
"""Open-loop (fixed-rate) latency harness — the vegeta analogue.

The reference ships `benchmark.sh` (vegeta: 50 rps x 30 s POST of a 1080p
JPEG against /crop, /resize, /extract — /root/reference/benchmark.sh:16-31).
This harness reproduces that shape against OUR live HTTP server, plus the
4-op /pipeline chain of BASELINE.json config #3, and reports p50/p95/p99
per route. Open-loop means requests fire on a fixed clock regardless of
completions — queueing delay shows up in the tail instead of silently
throttling the offered load, which is what the p99 <= 2x-baseline target
(BASELINE.md) is defined against.

Usage:
    python bench_latency.py                # 20 rps x 15 s per route
    BENCH_RATE=50 BENCH_SECS=30 python bench_latency.py

Output: one JSON line per route on stdout; human detail on stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

import numpy as np

ROUTES = [
    # (name, path+query, method)
    ("resize", "/resize?width=300&height=200", "POST"),
    ("crop", "/crop?width=400&height=300", "POST"),
    ("extract", "/extract?top=100&left=100&areawidth=600&areaheight=400", "POST"),
    (
        "pipeline",
        "/pipeline?operations=" + __import__("urllib.parse", fromlist=["quote"]).quote(
            json.dumps(
                [
                    {"operation": "crop", "params": {"width": 1600, "height": 900}},
                    {"operation": "resize", "params": {"width": 640}},
                    {"operation": "blur", "params": {"sigma": 1.5}},
                    {"operation": "convert", "params": {"type": "jpeg"}},
                ]
            )
        ),
        "POST",
    ),
]


from bench_util import make_1080p_jpeg as _make_1080p_jpeg  # noqa: E402


from bench_util import pctl as _pctl  # noqa: E402


async def _fire(session, url, method, body, lats, errors):
    t0 = time.monotonic()
    try:
        async with session.request(method, url, data=body) as resp:
            await resp.read()
            if resp.status != 200:
                errors.append(resp.status)
                return
    except Exception:
        errors.append(-1)
        return
    lats.append((time.monotonic() - t0) * 1000.0)


async def run_route(base, name, pathq, method, body, rate, secs):
    import aiohttp

    lats: list = []
    errors: list = []
    interval = 1.0 / rate
    n = int(rate * secs)
    conn = aiohttp.TCPConnector(limit=0)
    async with aiohttp.ClientSession(connector=conn) as session:
        tasks = []
        t_start = time.monotonic()
        for i in range(n):
            # fixed-clock schedule: sleep until this request's slot
            slot = t_start + i * interval
            delay = slot - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(
                asyncio.create_task(
                    _fire(session, base + pathq, method, body, lats, errors)
                )
            )
        await asyncio.gather(*tasks)
    sent = n
    ok = len(lats)
    res = {
        "metric": f"latency_{name}_1080p_jpeg",
        "rate_rps": rate,
        "duration_s": secs,
        "sent": sent,
        "ok": ok,
        "errors": len(errors),
        "p50_ms": _pctl(lats, 0.50),
        "p95_ms": _pctl(lats, 0.95),
        "p99_ms": _pctl(lats, 0.99),
        "mean_ms": round(sum(lats) / ok, 2) if ok else 0.0,
    }
    return res


def baseline_latency(buf: bytes, n: int = 100) -> dict:
    """Single-op cv2 latency distribution on this host — the '1x' the
    p99 <= 2x target is measured against."""
    import cv2

    data = np.frombuffer(buf, np.uint8)
    lats = []
    for _ in range(n):
        t0 = time.monotonic()
        a = cv2.imdecode(data, cv2.IMREAD_COLOR)
        r = cv2.resize(a, (300, 200), interpolation=cv2.INTER_AREA)
        cv2.imencode(".jpg", r, [int(cv2.IMWRITE_JPEG_QUALITY), 80])
        lats.append((time.monotonic() - t0) * 1000.0)
    return {"p50_ms": _pctl(lats, 0.50), "p99_ms": _pctl(lats, 0.99)}


async def main_async():
    rate = float(os.environ.get("BENCH_RATE", "20"))
    secs = float(os.environ.get("BENCH_SECS", "15"))
    port = int(os.environ.get("BENCH_PORT", "8899"))

    platform = os.environ.get("BENCH_PLATFORM", "")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    from aiohttp import web as aioweb

    from imaginary_tpu.web.app import create_app
    from imaginary_tpu.web.config import ServerOptions

    o = ServerOptions(port=port)
    app = create_app(o)
    runner = aioweb.AppRunner(app)
    await runner.setup()
    site = aioweb.TCPSite(runner, "127.0.0.1", port)
    await site.start()

    buf = _make_1080p_jpeg()
    base_url = f"http://127.0.0.1:{port}"

    # warm every route's compile cache before the clock starts
    import aiohttp

    async with aiohttp.ClientSession() as s:
        for name, pathq, method in ROUTES:
            async with s.request(method, base_url + pathq, data=buf) as r:
                await r.read()
                if r.status != 200:
                    print(f"[lat] warmup {name} -> {r.status}", file=sys.stderr)

    base = baseline_latency(buf)
    print(f"[lat] cv2 baseline: p50={base['p50_ms']}ms p99={base['p99_ms']}ms",
          file=sys.stderr)

    results = []
    for name, pathq, method in ROUTES:
        res = await run_route(base_url, name, pathq, method, buf, rate, secs)
        res["baseline_p99_ms"] = base["p99_ms"]
        res["p99_vs_2x_baseline"] = (
            "PASS" if res["p99_ms"] <= 2 * base["p99_ms"] else "FAIL"
        )
        results.append(res)
        print(f"[lat] {name}: p50={res['p50_ms']} p95={res['p95_ms']} "
              f"p99={res['p99_ms']} ok={res['ok']}/{res['sent']} "
              f"({res['p99_vs_2x_baseline']} vs 2x baseline p99)", file=sys.stderr)

    await runner.cleanup()
    for res in results:
        print(json.dumps(res))


if __name__ == "__main__":
    asyncio.run(main_async())
